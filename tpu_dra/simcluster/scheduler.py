"""DRA scheduler sim: claims-from-templates, device allocation, binding.

Stands in for the upstream kube-scheduler's DRA plugin + the
kube-controller-manager's resourceclaim controller (neither is driver
code — SURVEY §1: "there is no scheduler code to rebuild"). Allocation
follows the real algorithm's observable behavior: DeviceClass CEL
selectors are matched against device attributes published in
ResourceSlices, devices already referenced by any allocated claim are
excluded, and the pod binds to a node that can satisfy every claim.

Two drive modes (SURVEY §10):

- **event mode** (``start()``) — the production shape, mirroring the
  reference's informer/workqueue controllers: informers watch Pods /
  ResourceClaims / ResourceSlices / DeviceClasses / Nodes, only dirty
  pods are enqueued, and the allocated-device set lives in an
  **incremental AllocationIndex** maintained from claim watch events
  (plus the scheduler's own writes, mutation-cache style) instead of
  being recomputed from a full claim list per attempt. Claim GC runs
  from pod-delete events with a low-frequency sweep as the safety net.
  Steady state performs ZERO full relists (metrics:
  ``tpu_dra_sched_full_relists``); the index falls back to a guarded
  full resync only when an event is known-dropped or an index apply
  fails (fault sites ``sched.watch_event`` / ``sched.index_apply``).

- **sync mode** (``reconcile_once()`` on an unstarted scheduler, or
  ``start(mode="poll")``) — the poll-and-scan path kept for unit tests
  and as the ultimate fallback: full-lists Pods and ResourceClaims and
  rebuilds a transient index per pass. Every pass counts as a full
  relist.

CEL selector evaluation is compile-cached (simcluster.cel): expressions
parse once per distinct source string; allocation evaluates the cached
AST per candidate device. Per-DeviceClass selector sources are
additionally cached keyed by the class's resourceVersion.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpu_dra.infra import featuregates
from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.metrics import (
    SCHED_CLAIMS_GCED, SCHED_FULL_RELISTS, SCHED_PODS_BOUND,
    SCHED_WATCH_EVENTS, TOPO_ALLOCS, TOPO_FREE_CUBOID, TOPO_SCORE_SECONDS,
    Timer,
)
from tpu_dra.infra.workqueue import (
    ExponentialFailureRateLimiter, WorkQueue,
)
from tpu_dra.k8s.client import (
    AlreadyExistsError, ApiClient, ConflictError, NotFoundError,
)
from tpu_dra.k8s.informer import Informer
from tpu_dra.k8s.resources import (
    DEVICECLASSES, NODES, PODS, RESOURCECLAIMS, RESOURCECLAIMTEMPLATES,
    RESOURCESLICES,
)
from tpu_dra.simcluster import cel
from tpu_dra import topology

log = logging.getLogger("simcluster.scheduler")

_Entry = Tuple[str, str, str]  # (driver, pool, device)


def _parent_of(device: str) -> str:
    """Subslice devices ('chip-N-ss...') partition their parent chip
    ('chip-N'); everything else is its own parent."""
    return device.split("-ss")[0] if "-ss" in device else device


def _expand(entries: Iterable[_Entry]) -> List[_Entry]:
    """Allocation entries plus their partition-semantics block markers
    (the DRA partitionable-device counter analog): a whole-chip
    allocation blocks its subslices (marker '<chip>-ss*') and a subslice
    blocks the whole chip (marker = parent name), while two different
    subslices of one chip can coexist (MIG-style)."""
    out: List[_Entry] = []
    for driver, pool, name in entries:
        out.append((driver, pool, name))
        parent = _parent_of(name)
        out.append((driver, pool, parent) if parent != name
                   else (driver, pool, f"{name}-ss*"))
    return out


def claim_key(obj: Dict) -> str:
    meta = obj.get("metadata", {})
    return f"{meta.get('namespace', 'default')}/{meta['name']}"


def claim_entries(claim: Dict) -> Tuple[_Entry, ...]:
    """The (driver, pool, device) results of a claim's allocation
    (empty when unallocated)."""
    alloc = (claim.get("status") or {}).get("allocation") or {}
    return tuple(
        (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
        for r in (alloc.get("devices") or {}).get("results") or [])


class AllocationIndex:
    """Incremental allocated-device index, maintained from ResourceClaim
    add/update/delete events instead of re-listing all claims per
    scheduling attempt.

    Holds only extracted string tuples (never references to cache
    objects), refcounted so that two subslice claims on one chip keep
    the parent-chip block marker alive until BOTH release. ``apply`` is
    idempotent per claim key (replace semantics), which makes informer
    relists — which re-dispatch adds for every object — safe to feed
    straight in.

    ``dirty`` flags a known divergence (a dropped watch event, a failed
    apply): allocation must not proceed until ``resync`` rebuilds from a
    full claim listing (the guarded fallback).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_claim: Dict[str, Tuple[_Entry, ...]] = {}
        self._taken: Dict[_Entry, int] = {}
        # Per-claim resourceVersion high-water mark: the scheduler
        # applies its OWN writes synchronously (mutation-cache style),
        # so the watch event for an EARLIER state of the same claim can
        # arrive afterwards on the informer thread — applying it would
        # roll the allocation back and let another pod double-allocate
        # the device. Numeric-RV monotonicity guards every apply/remove.
        self._rv: Dict[str, int] = {}
        # FIFO of keys whose allocation is gone but whose watermark is
        # retained (anti-resurrection for in-flight stale events). The
        # steady state is designed to NEVER resync, so without eviction
        # one watermark per claim-ever-seen would leak; beyond the
        # horizon a stale event for the claim can no longer be in
        # flight, so the oldest watermarks are safe to drop.
        self._removed: "deque[str]" = deque()
        # Bumped on every EFFECTIVE mutation: lets a resync detect that
        # an informer-thread apply/remove landed between its lister
        # snapshot and its swap (which would otherwise be silently
        # resurrected by the wholesale replace).
        self._mutations = 0
        self.dirty = False
        self.dirty_reason = ""

    RV_RETENTION = 4096  # evicted-claim watermarks kept (FIFO)

    # -- mutation -----------------------------------------------------------

    def _add(self, expanded: List[_Entry]) -> None:
        for e in expanded:
            self._taken[e] = self._taken.get(e, 0) + 1

    def _sub(self, expanded: List[_Entry]) -> None:
        for e in expanded:
            n = self._taken.get(e, 0) - 1
            if n > 0:
                self._taken[e] = n
            else:
                self._taken.pop(e, None)

    def _note_removed_locked(self, key: str) -> None:
        self._removed.append(key)
        while len(self._removed) > self.RV_RETENTION:
            old = self._removed.popleft()
            if old not in self._by_claim:  # not re-created since
                self._rv.pop(old, None)

    # ONE resourceVersion parse for both halves of the mutation-cache
    # discipline: the informer's STALE guard and this index's watermark
    # must agree on ordering or one layer accepts what the other rejects.
    _rv_int = staticmethod(Informer._rv_int)

    def _stale_locked(self, key: str, claim: Dict) -> bool:
        rv = self._rv_int(claim)
        if rv is None:
            return False
        if rv < self._rv.get(key, 0):
            return True
        self._rv[key] = rv
        return False

    def apply(self, claim: Dict) -> None:
        """Add/replace one claim's allocation. Consults the
        ``sched.index_apply`` fault site — a raised fault leaves the
        index UNCHANGED (the caller marks it dirty and resyncs).
        Applies carrying an older resourceVersion than already indexed
        are ignored (see _rv above)."""
        key = claim_key(claim)
        FAULTS.check("sched.index_apply", claim=key)
        entries = claim_entries(claim)
        with self._lock:
            if self._stale_locked(key, claim):
                return
            old = self._by_claim.get(key)
            if old == entries:
                return
            self._mutations += 1
            if old:
                self._sub(_expand(old))
            if entries:
                self._add(_expand(entries))
                self._by_claim[key] = entries
            elif old is not None:
                self._by_claim.pop(key, None)
                self._note_removed_locked(key)

    def remove(self, claim: Dict, force: bool = False) -> None:
        """Drop a claim's allocation. ``force=True`` is for the
        scheduler mirroring its OWN client.delete (the delete's RV is
        unknowable — the verb returns nothing), so the staleness guard
        is bypassed and the high-water mark advanced to at least the
        deleted object's RV; single-writer discipline makes that safe."""
        key = claim_key(claim)
        FAULTS.check("sched.index_apply", claim=key)
        with self._lock:
            if force:
                rv = self._rv_int(claim)
                if rv:
                    self._rv[key] = max(self._rv.get(key, 0), rv)
            elif self._stale_locked(key, claim):
                return
            self._mutations += 1  # watermark advance alone must also
            #   invalidate an in-flight resync snapshot
            old = self._by_claim.pop(key, None)
            if old:
                self._sub(_expand(old))
            self._note_removed_locked(key)

    def begin_resync(self) -> None:
        """Clear the dirty flag BEFORE the caller takes its claim
        snapshot: a concurrent _mark_dirty whose dropped event postdates
        the snapshot then re-dirties the index and its queued resync
        re-runs — clearing after the swap would clobber that mark and
        leave the index divergent forever."""
        with self._lock:
            self.dirty = False
            self.dirty_reason = ""

    def mutation_count(self) -> int:
        with self._lock:
            return self._mutations

    def resync(self, claims: Iterable[Dict],
               only_if_mutations: Optional[int] = None) -> bool:
        """Rebuild from a full claim listing (call begin_resync first).
        Deliberately does NOT consult the fault site: this IS the
        recovery path — an armed apply fault must not be able to starve
        it. Does NOT touch the dirty flag (see begin_resync).

        only_if_mutations: the mutation_count() the caller read BEFORE
        taking its claim snapshot; the swap is refused (returns False)
        when a concurrent apply/remove landed in between — wholesale
        replacement would silently resurrect what that mutation
        changed (e.g. an out-of-band claim delete)."""
        by_claim: Dict[str, Tuple[_Entry, ...]] = {}
        taken: Dict[_Entry, int] = {}
        rvs: Dict[str, int] = {}
        for claim in claims:
            key = claim_key(claim)
            rv = self._rv_int(claim)
            if rv:
                rvs[key] = rv
            entries = claim_entries(claim)
            if not entries:
                continue
            by_claim[key] = entries
            for e in _expand(entries):
                taken[e] = taken.get(e, 0) + 1
        with self._lock:
            if (only_if_mutations is not None
                    and self._mutations != only_if_mutations):
                return False
            self._by_claim = by_claim
            self._taken = taken
            self._rv = rvs
            self._removed.clear()
        return True

    # -- queries ------------------------------------------------------------

    def is_taken(self, driver: str, pool: str, name: str,
                 overlay: Optional[Set[_Entry]] = None) -> bool:
        key = (driver, pool, name)
        parent = _parent_of(name)
        marker = (driver, pool, f"{parent}-ss*") if parent != name else None
        with self._lock:
            if key in self._taken:
                return True
            if marker and marker in self._taken:
                return True  # parent chip wholly claimed
        if overlay:
            if key in overlay:
                return True
            if marker and marker in overlay:
                return True
        return False

    def entries_for(self, key: str) -> Tuple[_Entry, ...]:
        with self._lock:
            return self._by_claim.get(key, ())

    def owners_of_pool(self, pool: str) -> Set[str]:
        """Claim keys holding any device on `pool` (diagnostics)."""
        with self._lock:
            return {k for k, entries in self._by_claim.items()
                    if any(e[1] == pool for e in entries)}

    def diff_against(self, claims: Iterable[Dict]) -> List[str]:
        """Divergences between the live index and a ground-truth claim
        listing (chaos invariant: after quiesce, empty)."""
        want: Dict[str, Tuple[_Entry, ...]] = {}
        for claim in claims:
            entries = claim_entries(claim)
            if entries:
                want[claim_key(claim)] = entries
        with self._lock:
            have = dict(self._by_claim)
        out = []
        for key in sorted(set(want) | set(have)):
            if want.get(key) != have.get(key):
                out.append(f"index[{key}]={have.get(key)} != "
                           f"truth {want.get(key)}")
        return out


class _Unscheduled(Exception):
    """Internal: transient condition (conflict, missing object) — let the
    workqueue retry with backoff."""


class Scheduler:
    """See module docstring. ``interval`` is the poll-mode cadence (and
    the legacy constructor signature); ``resync_interval`` is the
    event-mode safety-net cadence at which still-pending pods are
    re-nudged; ``gc_sweep_interval`` paces the low-frequency orphan-claim
    sweep backing the event-driven GC."""

    SYNC_TIMEOUT = 10.0

    def __init__(self, client: ApiClient, interval: float = 0.15, *,
                 resync_interval: float = 2.0,
                 gc_sweep_interval: float = 10.0):
        self._client = client
        self._interval = interval
        self._resync_interval = resync_interval
        self._gc_sweep_interval = gc_sweep_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[WorkQueue] = None
        self._worker: Optional[threading.Thread] = None
        self._sweeper: Optional[threading.Thread] = None
        self._informers: Dict[str, Informer] = {}
        self._index = AllocationIndex()
        self._pending: Set[str] = set()
        # Pods fully placed by us: their own bind-event echo must not
        # re-enqueue a full reconcile pass (entries leave on pod delete,
        # so the set is bounded by live placed pods).
        self._done: Set[str] = set()
        self._plock = threading.Lock()
        # DeviceClass name -> (resourceVersion, selector sources): spares
        # re-extracting selector lists per allocation; the compiled
        # programs themselves are cached process-wide in simcluster.cel.
        self._class_cache: Dict[str, Tuple[str, List[str]]] = {}
        # Node -> (slice (name, rv) fingerprint, NodeTopology|None): the
        # per-node fabric view extracted from published ResourceSlices,
        # rebuilt only when a slice's resourceVersion moves. Worker-thread
        # only (same single-writer discipline as _class_cache).
        self._topo_cache: Dict[
            str, Tuple[tuple, Optional[topology.NodeTopology]]] = {}
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    def start(self, mode: str = "events") -> None:
        self._stop.clear()  # both modes: a restart after stop() must run
        if mode == "poll":
            self._thread = threading.Thread(target=self._poll_run,
                                            daemon=True,
                                            name="sim-scheduler")
            self._thread.start()
            return
        # Fresh state for (re)start: informers begin with empty stores,
        # so nothing would ever dispatch deletes for claims that died
        # while the scheduler was stopped — a retained index would keep
        # their devices phantom-allocated forever.
        self._index = AllocationIndex()
        with self._plock:
            self._pending.clear()
            self._done.clear()
        self._class_cache.clear()
        self._topo_cache.clear()
        self._queue = WorkQueue(
            # No global token bucket: event enqueues are explicit-delay
            # (after=0) and failures back off per item; a bucket would
            # throttle churn-scale nudge fan-in for no protection (the
            # "apiserver" here is in-process or the fake).
            rate_limiter=ExponentialFailureRateLimiter(0.005, 2.0),
            log=lambda msg: log.debug("workqueue: %s", msg))

        inf = {}
        for name, gvr in (("pods", PODS), ("claims", RESOURCECLAIMS),
                          ("slices", RESOURCESLICES),
                          ("classes", DEVICECLASSES), ("nodes", NODES)):
            inf[name] = Informer(self._client, gvr,
                                 copy_on_read=False, copy_events=False)
        inf["claims"].add_indexer("owner", self._owner_index)
        inf["slices"].add_indexer("node", self._slice_node_index)

        inf["pods"].on_add(self._on_pod)
        inf["pods"].on_update(lambda old, new: self._on_pod(new))
        inf["pods"].on_delete(self._on_pod_deleted)
        inf["claims"].on_add(lambda obj: self._on_claim(None, obj))
        inf["claims"].on_update(self._on_claim)
        inf["claims"].on_delete(self._on_claim_deleted)
        for src in ("slices", "nodes"):
            inf[src].on_add(lambda obj, s=src: self._on_capacity(s))
            inf[src].on_update(lambda o, n, s=src: self._on_capacity(s))
            inf[src].on_delete(lambda obj, s=src: self._on_capacity(s))
        inf["classes"].on_add(lambda obj: self._on_class(obj))
        inf["classes"].on_update(lambda o, n: self._on_class(n))
        inf["classes"].on_delete(lambda obj: self._on_class(obj))

        self._informers = inf
        self._started = True
        self._worker = threading.Thread(
            target=self._queue.run, args=(self._stop,), daemon=True,
            name="sim-scheduler-worker")
        self._worker.start()
        for i in inf.values():
            i.start()
        for i in inf.values():
            i.wait_for_sync(self.SYNC_TIMEOUT)
        # The initial claim listing flowed through _on_claim adds during
        # informer sync, so the index is already built; the nudge below
        # only covers pods whose add events raced the pending-set wiring.
        self._nudge_pending_pods()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True,
                                         name="sim-scheduler-sweep")
        self._sweeper.start()

    def stop(self) -> None:
        self._stop.set()
        for i in self._informers.values():
            i.stop()
        if self._queue is not None:
            self._queue.shutdown()
        for t in (self._worker, self._sweeper, self._thread):
            if t is not None:
                t.join(timeout=5)
        self._started = False

    def _poll_run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("scheduler reconcile failed")

    # -- event handlers (watch threads: derive keys, enqueue, return) -------

    @staticmethod
    def _owner_index(obj: Dict) -> List[str]:
        owner = (obj.get("metadata", {}).get("annotations") or {}).get(
            "sim/owner-pod")
        if not owner:
            return []
        ns = obj["metadata"].get("namespace", "default")
        return [f"{ns}/{owner}"]

    @staticmethod
    def _slice_node_index(obj: Dict) -> List[str]:
        node = (obj.get("spec") or {}).get("nodeName")
        return [node] if node else []

    def _drop_event(self, resource: str) -> bool:
        """The sched.watch_event chaos seam: a fired site models the
        scheduler mishandling this event. The event is dropped BUT the
        index is marked dirty — the guard knows it dropped something, so
        the full-resync fallback takes over before the next allocation
        (that is what makes the fallback 'guarded')."""
        if FAULTS.fires("sched.watch_event"):
            self._mark_dirty(f"watch event dropped ({resource})")
            return True
        SCHED_WATCH_EVENTS.inc(labels={"resource": resource})
        return False

    def _on_pod(self, pod: Dict) -> None:
        if self._drop_event("pods"):
            return
        if pod["metadata"].get("deletionTimestamp"):
            return
        key = self._pod_key(pod)
        phase = (pod.get("status") or {}).get("phase", "Pending")
        if phase not in ("", "Pending"):
            self._forget_pod(key)
            return
        if pod["spec"].get("nodeName"):
            with self._plock:
                if key in self._done:
                    return  # our own bind/status echo: already placed
        self._enqueue_pod(key)

    def _on_pod_deleted(self, pod: Dict) -> None:
        if self._drop_event("pods"):
            return
        key = self._pod_key(pod)
        self._forget_pod(key)
        # Event-driven claim GC: the resourceclaim controller's ownerRef
        # analog, fired from the delete event instead of a 150ms
        # full-list poll; the periodic sweep stays as the safety net.
        self._queue.enqueue(key, self._gc_pod_claims, key=f"gc/{key}",
                            after=0, dedupe=True)

    def _on_claim(self, old: Optional[Dict], new: Dict) -> None:
        if self._drop_event("resourceclaims"):
            return
        try:
            self._index.apply(new)
        except FaultInjected:
            self._mark_dirty("index apply failed")
            return
        if old is not None and claim_entries(old) and not claim_entries(new):
            self._nudge_pending_pods()  # deallocation freed devices

    def _on_claim_deleted(self, claim: Dict) -> None:
        if self._drop_event("resourceclaims"):
            return
        try:
            self._index.remove(claim)
        except FaultInjected:
            self._mark_dirty("index remove failed")
            return
        # A deleted claim may free devices — and if its owner pod is
        # still alive (out-of-band deletion), that pod needs re-driving
        # so its template claim is recreated.
        owner = (claim.get("metadata", {}).get("annotations") or {}).get(
            "sim/owner-pod")
        if owner:
            ns = claim["metadata"].get("namespace", "default")
            self._enqueue_pod(f"{ns}/{owner}")
        self._nudge_pending_pods()

    def _on_capacity(self, resource: str) -> None:
        if self._drop_event(resource):
            return
        self._nudge_pending_pods()

    def _on_class(self, dc: Dict) -> None:
        if self._drop_event("deviceclasses"):
            return
        self._class_cache.pop(dc["metadata"]["name"], None)
        self._nudge_pending_pods()

    # -- queue plumbing ------------------------------------------------------

    @staticmethod
    def _pod_key(pod: Dict) -> str:
        return (f"{pod['metadata'].get('namespace', 'default')}/"
                f"{pod['metadata']['name']}")

    def _enqueue_pod(self, key: str) -> None:
        with self._plock:
            self._pending.add(key)
            self._done.discard(key)
        self._queue.enqueue(key, self._process_pod, key=f"pod/{key}",
                            after=0, dedupe=True)

    def _forget_pod(self, key: str, done: bool = False) -> None:
        with self._plock:
            self._pending.discard(key)
            if done:
                self._done.add(key)
            else:
                self._done.discard(key)

    def _nudge_pending_pods(self) -> None:
        """Re-drive every still-pending pod (capacity may have freed).
        dedupe=True collapses event-storm fan-in to one queued item per
        pod."""
        with self._plock:
            keys = sorted(self._pending)
        for key in keys:
            self._queue.enqueue(key, self._process_pod, key=f"pod/{key}",
                                after=0, dedupe=True)

    def _mark_dirty(self, reason: str) -> None:
        self._index.dirty = True
        self._index.dirty_reason = reason
        if self._queue is not None:
            self._queue.enqueue(reason, lambda _: self._full_resync(),
                                key="resync", after=0, dedupe=True)

    def request_resync(self, reason: str = "requested") -> None:
        """Public seam (chaos op): force the guarded full-resync path."""
        self._mark_dirty(reason)

    def _full_resync(self) -> None:
        """The guarded fallback: rebuild the allocation index and the
        pending-pod set from the informer caches (which self-heal via
        relist even when the SCHEDULER mishandled events) and re-drive
        everything pending. Counted — the bench asserts steady state
        never comes here."""
        if not self._index.dirty:
            return
        SCHED_FULL_RELISTS.inc()
        reason = self._index.dirty_reason
        # Clear-dirty BEFORE the snapshot: a drop landing after the
        # listing re-dirties the index and its own queued resync re-runs.
        self._index.begin_resync()
        for _ in range(8):
            gen = self._index.mutation_count()
            if self._index.resync(self._list_claims(),
                                  only_if_mutations=gen):
                break
        else:
            # Concurrent mutations kept invalidating the snapshot
            # (effective handler-side changes are rare, so this is an
            # extreme tail): retry through the queue rather than spin.
            self._mark_dirty("resync raced concurrent index mutations")
            return
        with self._plock:
            self._pending.clear()
            self._done.clear()  # conservatively re-verify placed pods
        for pod in self._list_pods():
            if pod["metadata"].get("deletionTimestamp"):
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase in ("", "Pending"):
                self._enqueue_pod(self._pod_key(pod))
        log.info("full resync completed (%s)", reason)

    def _sweep_loop(self) -> None:
        next_gc = time.monotonic() + self._gc_sweep_interval
        while not self._stop.wait(self._resync_interval):
            self._nudge_pending_pods()
            if time.monotonic() >= next_gc:
                next_gc = time.monotonic() + self._gc_sweep_interval
                self._queue.enqueue(
                    "sweep", lambda _: self._gc_sweep(),
                    key="gc-sweep", after=0, dedupe=True)

    # -- data access (lister-backed when started, client-backed sync) --------

    def _list_pods(self) -> List[Dict]:
        if self._started:
            return self._informers["pods"].lister.list()
        return self._client.list(PODS)

    def _list_claims(self) -> List[Dict]:
        if self._started:
            return self._informers["claims"].lister.list()
        return self._client.list(RESOURCECLAIMS)

    def _get_pod(self, ns: str, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["pods"].lister.get(name, ns)
        try:
            return self._client.get(PODS, name, ns)
        except NotFoundError:
            return None

    def _get_claim(self, ns: str, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["claims"].lister.get(name, ns)
        try:
            return self._client.get(RESOURCECLAIMS, name, ns)
        except NotFoundError:
            return None

    def _iter_nodes(self) -> List[Dict]:
        nodes = (self._informers["nodes"].lister.list() if self._started
                 else self._client.list(NODES))
        return sorted(nodes, key=lambda n: n["metadata"]["name"])

    def _slices_for_node(self, node: str) -> List[Dict]:
        if self._started:
            return self._informers["slices"].get_by_index("node", node)
        return [sl for sl in self._client.list(RESOURCESLICES)
                if (sl.get("spec") or {}).get("nodeName") == node]

    def _get_class(self, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["classes"].lister.get(name)
        try:
            return self._client.get(DEVICECLASSES, name)
        except NotFoundError:
            return None

    # -- sync mode -----------------------------------------------------------

    def reconcile_once(self) -> None:
        """One poll-and-scan pass (sync/poll mode): full-list Pods and
        ResourceClaims, rebuild a transient allocation index, GC orphans,
        drive every pending pod. Event mode makes this the exception —
        each call counts on tpu_dra_sched_full_relists."""
        SCHED_FULL_RELISTS.inc()
        pods = self._client.list(PODS)
        claims = self._client.list(RESOURCECLAIMS)
        gced = self._gc_orphan_claims(pods, claims, path="sweep")
        self._index.begin_resync()
        self._index.resync(c for c in claims if claim_key(c) not in gced)
        for pod in pods:
            if pod["metadata"].get("deletionTimestamp"):
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase not in ("", "Pending"):
                continue
            try:
                pod = self._ensure_claims_from_templates(pod)
                self._schedule(pod)
            except (ConflictError, _Unscheduled):
                continue  # racing another write: next pass retries

    # -- claim GC -------------------------------------------------------------

    def _gc_pod_claims(self, key: str) -> None:
        """Event path: the pod named by `key` is gone; delete the claims
        it owns (owner index lookup, no listing)."""
        for claim in self._informers["claims"].get_by_index("owner", key):
            self._delete_claim(claim, path="event")

    def _gc_sweep(self) -> None:
        """Safety-net sweep over the informer caches (NOT an apiserver
        list): catches claims whose pod-delete event was missed."""
        self._gc_orphan_claims(self._list_pods(), self._list_claims(),
                               path="sweep")

    def _gc_orphan_claims(self, pods: List[Dict], claims: List[Dict],
                          path: str = "sweep") -> Set[str]:
        """The resourceclaim controller's ownerRef GC analog: a claim
        generated from a template dies with its pod — otherwise exclusive
        devices (channel-0, the daemon device) stay allocated forever and
        the next workload can never schedule. Returns the keys of the
        claims deleted (so a sync pass excludes them from its index)."""
        alive = {(p["metadata"].get("namespace", "default"),
                  p["metadata"]["name"]) for p in pods
                 if not p["metadata"].get("deletionTimestamp")}
        gced: Set[str] = set()
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if not owner:
                continue
            ns = claim["metadata"].get("namespace", "default")
            if (ns, owner) not in alive:
                self._delete_claim(claim, path=path)
                gced.add(claim_key(claim))
        return gced

    def _delete_claim(self, claim: Dict, path: str) -> None:
        ns = claim["metadata"].get("namespace", "default")
        name = claim["metadata"]["name"]
        try:
            self._client.delete(RESOURCECLAIMS, name, ns)
        except NotFoundError:
            return
        # Mirror our own delete into the index synchronously (the write
        # half of the mutation-cache discipline): with creates, status
        # writes AND deletes all applied on the worker thread, the
        # informer-thread handlers only ever replay states the index has
        # already seen — so a full resync can never race a real mutation.
        try:
            self._index.remove(claim, force=True)
        except FaultInjected:
            self._mark_dirty("index remove failed (own delete)")
        SCHED_CLAIMS_GCED.inc(labels={"path": path})
        log.info("GC claim %s/%s via %s (owner pod gone)", ns, name, path)

    # -- per-pod reconcile (worker thread) ------------------------------------

    def _process_pod(self, key: str) -> None:
        # Never allocate over a known-divergent index: resync first
        # (same worker thread, so this is naturally serialized with all
        # other allocation).
        if self._index.dirty:
            self._full_resync()
            if self._index.dirty:  # resync raced mutations; retry later
                raise _Unscheduled("index dirty, resync pending")
        ns, name = key.split("/", 1)
        pod = self._get_pod(ns, name)
        if pod is None or pod["metadata"].get("deletionTimestamp"):
            self._forget_pod(key)
            return
        phase = (pod.get("status") or {}).get("phase", "Pending")
        if phase not in ("", "Pending"):
            self._forget_pod(key)
            return
        try:
            pod = self._ensure_claims_from_templates(pod)
            done = self._schedule(pod)
        except (ConflictError, _Unscheduled) as e:
            raise _Unscheduled(str(e)) from e  # workqueue retries w/ backoff
        if done:
            self._forget_pod(key, done=True)
        # else: stays pending; capacity events / the periodic nudge
        # re-drive it — no busy retry for genuinely unschedulable pods.

    # -- resourceclaim controller analog --------------------------------------

    def _ensure_claims_from_templates(self, pod: Dict) -> Dict:
        """Create template-backed claims the pod is missing; returns the
        (possibly refreshed) pod object. Zero-copy discipline: `pod` may
        be a lister view — it is deepcopied before any mutation."""
        ns = pod["metadata"].get("namespace", "default")
        statuses = ((pod.get("status") or {})
                    .get("resourceClaimStatuses") or [])
        known = {s["name"]: s["resourceClaimName"] for s in statuses}
        changed = False
        for entry in (pod["spec"].get("resourceClaims") or []):
            if entry.get("resourceClaimName"):
                continue
            tmpl_name = entry.get("resourceClaimTemplateName")
            if not tmpl_name:
                continue
            if entry["name"] in known:
                # Status says the claim exists; recreate it if it was
                # deleted out-of-band while the pod lives on.
                if self._get_claim(ns, known[entry["name"]]) is not None:
                    continue
            try:
                rct = self._client.get(RESOURCECLAIMTEMPLATES, tmpl_name, ns)
            except NotFoundError:
                continue  # template not stamped yet; retried by nudge
            claim_name = known.get(entry["name"]) or (
                f"{pod['metadata']['name']}-{entry['name']}")
            claim = {
                "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name, "namespace": ns,
                    "labels": dict((rct["metadata"].get("labels") or {})),
                    "annotations": {
                        "resource.kubernetes.io/pod-claim-name":
                            entry["name"],
                        "sim/owner-pod": pod["metadata"]["name"]},
                },
                "spec": (rct.get("spec") or {}).get("spec") or {},
            }
            try:
                created = self._client.create(RESOURCECLAIMS, claim,
                                              namespace=ns)
                self._after_claim_write(created)
            except (ConflictError, AlreadyExistsError):
                pass  # racing create (retry, superseded worker): converged
            known[entry["name"]] = claim_name
            changed = True
        if changed:
            upd = copy.deepcopy(pod)
            upd.setdefault("status", {})["resourceClaimStatuses"] = [
                {"name": k, "resourceClaimName": v}
                for k, v in sorted(known.items())]
            pod = self._client.update_status(PODS, upd, ns)
            if self._started:
                self._informers["pods"].update_cache(pod)
        return pod

    # -- allocation + binding -------------------------------------------------

    def _schedule(self, pod: Dict) -> bool:
        """Returns True when the pod is fully placed (bound, claims
        allocated); False when it must wait for capacity."""
        ns = pod["metadata"].get("namespace", "default")
        claims = self._pod_claims(pod, ns)
        if claims is None:
            raise _Unscheduled("claim object missing")  # retried
        node_name = pod["spec"].get("nodeName")
        candidates = ([node_name] if node_name
                      else self._candidate_nodes(pod))
        for node in candidates:
            if self._try_allocate_all(claims, node):
                if not node_name:
                    upd = copy.deepcopy(pod)
                    upd["spec"]["nodeName"] = node
                    updated = self._client.update(PODS, upd, ns)
                    if self._started:
                        self._informers["pods"].update_cache(updated)
                    SCHED_PODS_BOUND.inc()
                return True
        return False

    def _pod_claims(self, pod: Dict, ns: str) -> Optional[List[Dict]]:
        statuses = {s["name"]: s["resourceClaimName"] for s in
                    ((pod.get("status") or {})
                     .get("resourceClaimStatuses") or [])}
        out = []
        for entry in (pod["spec"].get("resourceClaims") or []):
            name = entry.get("resourceClaimName") or statuses.get(
                entry["name"])
            if name is None:
                # Template-backed claim not created yet.
                if entry.get("resourceClaimTemplateName"):
                    return None
                continue
            claim = self._get_claim(ns, name)
            if claim is None:
                return None
            out.append(claim)
        return out

    def _candidate_nodes(self, pod: Dict) -> List[str]:
        selector = pod["spec"].get("nodeSelector") or {}
        names = []
        for node in self._iter_nodes():
            labels = node["metadata"].get("labels") or {}
            if all(labels.get(k) == v for k, v in selector.items()):
                names.append(node["metadata"]["name"])
        if (len(names) > 1
                and featuregates.enabled(
                    featuregates.TopologyAwareScheduling)):
            # Inter-node ICI adjacency: group candidates by the physical
            # slice their chips report, biggest slice group first, worker
            # order within — the pods of a multi-node ComputeDomain then
            # fill ONE slice in rank order instead of scattering across
            # slices in node-name order.
            infos = []
            for name in names:
                topo = self._node_topology(name)
                infos.append((name, topo.slice_id if topo else "",
                              topo.worker_index if topo else 0))
            return topology.rank_candidate_nodes(infos)
        return names

    def _node_topology(self, node: str) -> Optional[topology.NodeTopology]:
        """This node's fabric view (mesh + device-name<->coord maps) from
        its published ResourceSlices; None when the node publishes no
        usable coordinates. Cached against the slices' resourceVersions.
        Worker-thread only."""
        slices = self._slices_for_node(node)
        key = tuple(sorted(
            (sl["metadata"]["name"],
             sl["metadata"].get("resourceVersion", "")) for sl in slices))
        cached = self._topo_cache.get(node)
        if cached is not None and cached[0] == key:
            return cached[1]
        topo = topology.node_topology_from_slices(slices)
        self._topo_cache[node] = (key, topo)
        return topo

    def _try_allocate_all(self, claims: List[Dict], node: str) -> bool:
        """Allocate every unallocated claim on `node`; all-or-nothing per
        pod (claims already allocated elsewhere pin the pod implicitly:
        a shared pre-allocated claim simply must exist on this node).
        Device availability comes from the incremental index plus a
        staging overlay for this pod's own picks."""
        overlay: Set[_Entry] = set()
        staged: List[Tuple[Dict, Dict]] = []
        for claim in claims:
            alloc = (claim.get("status") or {}).get("allocation")
            if alloc:
                # Shared claim already allocated: usable only if it landed
                # on this node's pool.
                pools = {r.get("pool") for r in
                         (alloc.get("devices") or {}).get("results") or []}
                if pools and node not in pools:
                    return False
                continue
            allocation = self._allocate(claim, node, overlay)
            if allocation is None:
                return False
            staged.append((claim, allocation))
        for claim, allocation in staged:
            upd = copy.deepcopy(claim)
            upd.setdefault("status", {})["allocation"] = allocation
            updated = self._client.update_status(
                RESOURCECLAIMS, upd, upd["metadata"].get("namespace"))
            self._after_claim_write(updated)
        return True

    def _after_claim_write(self, obj: Dict) -> None:
        """Mutation-cache discipline for the scheduler's own writes: the
        informer cache AND the allocation index see the write before the
        watch event lands — the index never lags the scheduler's own
        allocations, which is what makes single-writer allocation safe
        on an event-driven cache. (In sync mode the index update keeps
        later pods in the SAME pass from re-picking the devices.)"""
        if self._started:
            self._informers["claims"].update_cache(obj)
        try:
            self._index.apply(obj)
        except FaultInjected:
            self._mark_dirty("index apply failed (own write)")

    def _allocate(self, claim: Dict, node: str,
                  overlay: Set[_Entry]) -> Optional[Dict]:
        devices = (claim.get("spec") or {}).get("devices") or {}
        results = []
        for req in devices.get("requests") or []:
            exact = req.get("exactly") or req  # v1 wrapper or flat
            class_name = exact.get("deviceClassName", "")
            count = int(exact.get("count") or 1)
            sources = self._class_selector_sources(class_name)
            if sources is None:
                return None
            # Per-request selectors AND with the class's (the real
            # allocator's semantics: every selector must match;
            # gpu-test6-style attribute selection rides here).
            sources = sources + [
                (sel.get("cel") or {}).get("expression", "")
                for sel in exact.get("selectors") or []]
            progs = cel.compile_many(sources)
            if progs is None:
                return None  # a broken selector selects nothing
            picked = self._pick_devices(node, progs, count, overlay)
            if picked is None:
                return None
            for driver, dev in picked:
                overlay.update(_expand([(driver, node, dev)]))
                results.append({"request": req["name"], "driver": driver,
                                "pool": node, "device": dev})
        if not results:
            return None
        config = [{"source": "FromClaim", **entry}
                  for entry in devices.get("config") or []]
        return {"devices": {"results": results, "config": config},
                "nodeSelector": {"nodeSelectorTerms": [{"matchFields": [
                    {"key": "metadata.name", "operator": "In",
                     "values": [node]}]}]}}

    def _class_selector_sources(self, name: str) -> Optional[List[str]]:
        """All CEL expressions of the DeviceClass (None if the class does
        not exist — the claim is unallocatable, not unconstrained),
        cached per (name, resourceVersion)."""
        dc = self._get_class(name)
        if dc is None:
            self._class_cache.pop(name, None)
            return None
        rv = dc["metadata"].get("resourceVersion", "")
        cached = self._class_cache.get(name)
        if cached is not None and cached[0] == rv:
            return cached[1]
        sources = [(sel.get("cel") or {}).get("expression", "")
                   for sel in (dc.get("spec") or {}).get("selectors") or []]
        self._class_cache[name] = (rv, sources)
        return sources

    def _pick_devices(self, node: str, progs: List["cel.Program"],
                      count: int, overlay: Set[_Entry]
                      ) -> Optional[List[Tuple[str, str]]]:
        """Devices on `node` matching EVERY compiled CEL program, as
        (driver, name) pairs. CEL is evaluated for real against the
        published attributes (simcluster.cel): a wrong attribute name or
        type mismatch selects nothing instead of everything.

        Iteration is deterministic — slices and devices are scanned in
        name order — so first-fit picks and topology scores reproduce
        across runs and chaos seeds regardless of dict/watch ordering.

        With the TopologyAwareScheduling gate on, multi-chip requests on
        a node that publishes chip coordinates take the topology-scored
        path: the pick must be an ICI-contiguous cuboid, chosen by the
        fragmentation score (tpu_dra.topology.best_placement). No cuboid
        fits -> the claim WAITS (None) rather than degrade to a
        scattered allocation; nodes without usable topology keep
        first-fit (counted as fallback)."""
        gate_on = (count > 1 and featuregates.enabled(
            featuregates.TopologyAwareScheduling))
        # A node with no usable topology keeps the first-fit early exit
        # even under the gate: scanning its whole inventory just to fall
        # back would turn O(count) picks into O(devices) on every
        # coordinate-less node (mixed fleets, sysfs without topology/).
        topo = self._node_topology(node) if gate_on else None
        topo_path = topo is not None
        available: List[Tuple[str, str]] = []
        for sl in sorted(self._slices_for_node(node),
                         key=lambda s: s["metadata"]["name"]):
            spec = sl.get("spec") or {}
            driver = spec.get("driver", "")
            for dev in sorted(spec.get("devices") or [],
                              key=lambda d: d["name"]):
                if not all(p.matches(dev, driver) for p in progs):
                    continue
                if self._index.is_taken(driver, node, dev["name"],
                                        overlay=overlay):
                    continue
                available.append((driver, dev["name"]))
                if not topo_path and len(available) == count:
                    if gate_on:
                        TOPO_ALLOCS.inc(labels={"outcome": "fallback"})
                    return available  # first-fit: done at count
        if len(available) < count:
            return None
        if not topo_path:
            return available[:count]
        return self._pick_topology(topo, available, count)

    def _pick_topology(self, topo: "topology.NodeTopology",
                       available: List[Tuple[str, str]],
                       count: int) -> Optional[List[Tuple[str, str]]]:
        """Topology-scored pick over the CEL-matched free devices."""
        if any(name not in topo.coord_of for _d, name in available):
            # The match includes devices the chip mesh cannot lay out
            # (subslices, foreign drivers): no fabric model for this
            # request — first-fit, honestly counted.
            TOPO_ALLOCS.inc(labels={"outcome": "fallback"})
            return available[:count]
        free = {topo.coord_of[name] for _d, name in available}
        with Timer(TOPO_SCORE_SECONDS):
            placed = topology.best_placement(topo.mesh, free, count)
            if placed is not None:
                # Observed inside the timed region: the free-cuboid scan
                # is the same order of work as the placement scan, and
                # leaving it outside would under-attribute the topology
                # path's real per-pick overhead.
                TOPO_FREE_CUBOID.observe(topology.max_free_cuboid(
                    topo.mesh, free.difference(placed)))
        if placed is None:
            TOPO_ALLOCS.inc(labels={"outcome": "unplaceable"})
            return None  # wait for a contiguous window, never scatter
        TOPO_ALLOCS.inc(labels={"outcome": "contiguous"})
        driver_of = dict((name, drv) for drv, name in available)
        return [(driver_of[topo.name_of[c]], topo.name_of[c])
                for c in placed]

    # -- introspection --------------------------------------------------------

    def verify_index(self) -> List[str]:
        """Divergences between the incremental index and cluster truth
        (a fresh apiserver claim listing); empty = consistent. Chaos
        invariant after quiesce."""
        return self._index.diff_against(self._client.list(RESOURCECLAIMS))

    def verify_topology(self) -> List[str]:
        """Topology invariants against cluster truth (chaos, after
        quiesce): (1) every allocated multi-chip claim on a node that
        publishes coordinates is an ICI-contiguous cuboid; (2) for each
        such node, the free coordinate set DERIVED from the incremental
        AllocationIndex equals the one derived from a fresh claim
        listing — the index owns allocation state (SURVEY §11), so a
        divergent derived free-set means the topology view (mesh/coord
        cache) broke, not the bookkeeping."""
        claims = self._client.list(RESOURCECLAIMS)
        slices = self._client.list(RESOURCESLICES)
        out = topology.allocation_violations(claims, slices)
        taken_truth: Dict[str, Set[str]] = {}
        for claim in claims:
            for _driver, pool, dev in claim_entries(claim):
                taken_truth.setdefault(pool, set()).add(_parent_of(dev))
        by_node: Dict[str, List[Dict]] = {}
        for sl in slices:
            node = (sl.get("spec") or {}).get("nodeName")
            if node:
                by_node.setdefault(node, []).append(sl)
        for node in sorted(by_node):
            topo = topology.node_topology_from_slices(by_node[node])
            if topo is None:
                continue
            free_truth = {c for name, c in topo.coord_of.items()
                          if name not in taken_truth.get(node, set())}
            free_index = {c for name, c in topo.coord_of.items()
                          if not self._index.is_taken(
                              topo.driver_of[name], node, name)}
            if free_truth != free_index:
                out.append(
                    f"topology free-set on {node} diverges from the "
                    f"allocation index: index-only "
                    f"{sorted(free_index - free_truth)}, truth-only "
                    f"{sorted(free_truth - free_index)}")
        return out

    def pending_pods(self) -> Set[str]:
        with self._plock:
            return set(self._pending)
