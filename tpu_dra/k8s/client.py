"""Kubernetes REST client over stdlib HTTP.

Replaces client-go's rest.Config/ClientSets (reference:
pkg/flags/kubeclient.go:33-147 builds Core/Nvidia/Resource clientsets from
either kubeconfig or in-cluster config). Objects are plain dicts
("unstructured"); typed behavior lives in the API layer.

Supports: CRUD + status subresource, JSON merge-patch, list with
label/field selectors, and streaming watch (chunked JSON lines), with
in-cluster service-account config discovery. ``RetryingApiClient`` wraps
any ApiClient (HTTP or fake) with jittered-backoff retry on transient
errors and a watch that reconnects resuming from the last seen
resourceVersion.
"""

from __future__ import annotations

import json
import os
import random
import socket
import ssl
import sys
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, Generator, List, Optional, Tuple

from tpu_dra.infra.faults import FAULTS, FaultInjected


@dataclass(frozen=True)
class GVR:
    """Group/version/resource coordinate; group '' = core."""
    group: str
    version: str
    plural: str
    namespaced: bool = True

    def path(self, namespace: Optional[str] = None, name: Optional[str] = None,
             subresource: Optional[str] = None) -> str:
        base = f"/api/{self.version}" if not self.group else f"/apis/{self.group}/{self.version}"
        parts = [base]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.plural)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    @property
    def key(self) -> str:
        return f"{self.group or 'core'}/{self.version}/{self.plural}"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status
        self.message = message


class NotFoundError(ApiError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class ConflictError(ApiError):
    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


class AlreadyExistsError(ApiError):
    def __init__(self, message: str = "already exists"):
        super().__init__(409, message)


def parse_label_selector(selector: str) -> List[Tuple[str, Optional[str]]]:
    """Parse 'k=v,k2,k3!=x' into [(key, value|None)] (None = exists).
    '!=' terms are represented as (key, ('!=', value))."""
    terms: List[Tuple[str, Any]] = []
    for part in filter(None, (p.strip() for p in (selector or "").split(","))):
        if "!=" in part:
            k, _, v = part.partition("!=")
            terms.append((k.strip(), ("!=", v.strip())))
        elif "=" in part:
            k, _, v = part.partition("=")
            terms.append((k.strip().rstrip("="), v.strip()))
        else:
            terms.append((part, None))
    return terms


def label_selector_matches(selector: Optional[str], labels: Dict[str, str]) -> bool:
    if not selector:
        return True
    for key, want in parse_label_selector(selector):
        if want is None:
            if key not in labels:
                return False
        elif isinstance(want, tuple):
            if labels.get(key) == want[1]:
                return False
        elif labels.get(key) != want:
            return False
    return True


_intern = sys.intern


def json_deepcopy(obj):
    """Deep copy for JSON-shaped API objects (dict/list containers,
    immutable scalars). copy.deepcopy's generic machinery (memo table,
    reduce protocol) dominated the fake apiserver at churn scale — this
    specialized walk is the same isolation at a fraction of the cost.
    Non-JSON containers (a tuple a test tucked into an object) are
    returned as-is: the API-object contract treats them as values.

    Dict KEYS are interned: API objects repeat the same field names
    ("metadata", "resourceVersion", "attributes", ...) across millions
    of copies at 10k-node churn scale, and interning collapses them to
    shared singletons — less allocation on the emit hot path and
    pointer-fast dict probes downstream. Keys only: the name universe
    is bounded (schema field names), while VALUES (pod names, RVs) grow
    without bound and would bloat the intern table forever."""
    cls = obj.__class__
    if cls is dict:
        return {_intern(k) if k.__class__ is str else k: json_deepcopy(v)
                for k, v in obj.items()}
    if cls is list:
        return [json_deepcopy(v) for v in obj]
    return obj


def parse_field_selector(selector: str) -> Tuple[Tuple[str, ...], str]:
    """Parse a single-term equality field selector ('spec.nodeName=n5',
    'metadata.name=x') into ((path, segments...), value). Only one
    ``path=value`` term is supported — exactly the shape the node-scoped
    consumers (kubelet pod watches, nodesim) use, and the shape the fake
    apiserver can index watch registration by. Anything else (set
    operators, conjunctions) raises ValueError loudly rather than
    silently matching everything."""
    if not selector or "=" not in selector or "!=" in selector \
            or "," in selector:
        raise ValueError(f"unsupported field selector {selector!r}: only "
                         "a single 'path=value' equality term is indexed")
    path, _, value = selector.partition("=")
    path = path.strip()
    if not path or not value:
        raise ValueError(f"unsupported field selector {selector!r}")
    return tuple(path.split(".")), value.strip()


def field_path_value(obj: Dict, path: Tuple[str, ...]) -> Optional[str]:
    """The object's value at a dotted field path, as a string, or None
    when absent/non-scalar. Shared by the fake apiserver's emit-side
    topic extraction and client-side field filtering so both sides of a
    field-selector watch agree on what a field 'is'."""
    cur = obj
    for seg in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(seg)
        if cur is None:
            return None
    if isinstance(cur, (dict, list)):
        return None
    return cur if isinstance(cur, str) else str(cur)


def field_selector_matches(selector: Optional[str], obj: Dict) -> bool:
    if not selector:
        return True
    path, want = parse_field_selector(selector)
    return field_path_value(obj, path) == want


class ApiClient:
    """Abstract client surface shared by HttpApiClient and FakeCluster."""

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def list(self, gvr: GVR, namespace: Optional[str] = None,
             label_selector: Optional[str] = None) -> List[Dict]:
        raise NotImplementedError

    def list_with_rv(self, gvr: GVR, namespace: Optional[str] = None,
                     label_selector: Optional[str] = None
                     ) -> Tuple[List[Dict], str]:
        """(items, collection resourceVersion). Default: no RV — watch then
        starts from 'now' (pre-RV behavior)."""
        return self.list(gvr, namespace, label_selector), ""

    def create(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def update(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def update_status(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def patch(self, gvr: GVR, name: str, patch: Dict,
              namespace: Optional[str] = None) -> Dict:
        """JSON merge-patch (RFC 7386)."""
        raise NotImplementedError

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None:
        raise NotImplementedError

    def watch(self, gvr: GVR, namespace: Optional[str] = None,
              label_selector: Optional[str] = None,
              resource_version: Optional[str] = None,
              stop: Optional[threading.Event] = None,
              field_selector: Optional[str] = None,
              ) -> Generator[Tuple[str, Dict], None, None]:
        """Yield (event_type, object): ADDED/MODIFIED/DELETED/BOOKMARK.

        ``field_selector`` is a single equality term ('spec.nodeName=n5');
        servers that index watch registration by field (the fake) use it
        to skip fan-out entirely for non-matching events."""
        raise NotImplementedError


# ---------------------------------------------------------------------------

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
IN_CLUSTER_NS = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"


class HttpApiClient(ApiClient):
    """Stdlib-HTTP client. Config resolution mirrors KubeClientConfig
    (kubeclient.go): explicit base URL flag > in-cluster env
    (KUBERNETES_SERVICE_HOST + service account files)."""

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None, ca_file: Optional[str] = None,
                 insecure: bool = False, timeout: float = 30.0):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise ValueError(
                    "no API server URL given and not running in-cluster")
            base_url = f"https://{host}:{port}"
            if token is None and os.path.exists(IN_CLUSTER_TOKEN):
                token = open(IN_CLUSTER_TOKEN).read().strip()
            if ca_file is None and os.path.exists(IN_CLUSTER_CA):
                ca_file = IN_CLUSTER_CA
        self._base = base_url.rstrip("/")
        self._token = token
        self._timeout = timeout
        if self._base.startswith("https"):
            if insecure:
                self._ssl = ssl._create_unverified_context()  # noqa: S323
            else:
                self._ssl = ssl.create_default_context(cafile=ca_file)
        else:
            self._ssl = None

    # -- plumbing -----------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[Dict] = None,
                 query: Optional[Dict[str, str]] = None,
                 content_type: str = "application/json") -> Dict:
        url = self._base + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout,
                                        context=self._ssl) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")
            if e.code == 404:
                raise NotFoundError(msg) from e
            if e.code == 409:
                # A real apiserver returns 409 for both optimistic-concurrency
                # conflicts and create-on-existing; distinguish by the Status
                # body's reason (client-go errors.IsAlreadyExists analog) so
                # callers' `except AlreadyExistsError` works over HTTP too.
                # Only the parsed Status reason is trusted: a substring test
                # on the raw body would misclassify a genuine stale-RV
                # Conflict whose object data happens to echo the phrase
                # "already exists".
                reason = ""
                try:
                    reason = json.loads(msg).get("reason", "")
                except (ValueError, AttributeError):
                    pass
                if reason == "AlreadyExists":
                    raise AlreadyExistsError(msg) from e
                raise ConflictError(msg) from e
            raise ApiError(e.code, msg) from e

    # -- verbs --------------------------------------------------------------

    def get(self, gvr, name, namespace=None):
        return self._request("GET", gvr.path(namespace, name))

    def list(self, gvr, namespace=None, label_selector=None):
        query = {}
        if label_selector:
            query["labelSelector"] = label_selector
        out = self._request("GET", gvr.path(namespace), query=query or None)
        return out.get("items", [])

    def create(self, gvr, obj, namespace=None):
        ns = namespace or obj.get("metadata", {}).get("namespace")
        return self._request("POST", gvr.path(ns), body=obj)

    def update(self, gvr, obj, namespace=None):
        meta = obj.get("metadata", {})
        ns = namespace or meta.get("namespace")
        return self._request("PUT", gvr.path(ns, meta["name"]), body=obj)

    def update_status(self, gvr, obj, namespace=None):
        meta = obj.get("metadata", {})
        ns = namespace or meta.get("namespace")
        return self._request("PUT", gvr.path(ns, meta["name"], "status"), body=obj)

    def patch(self, gvr, name, patch, namespace=None):
        return self._request("PATCH", gvr.path(namespace, name), body=patch,
                             content_type="application/merge-patch+json")

    def delete(self, gvr, name, namespace=None):
        try:
            self._request("DELETE", gvr.path(namespace, name))
        except NotFoundError:
            pass

    def list_with_rv(self, gvr, namespace=None, label_selector=None):
        """(items, resourceVersion) — the List response's collection RV, for
        gap-free list+watch resumption."""
        query = {}
        if label_selector:
            query["labelSelector"] = label_selector
        out = self._request("GET", gvr.path(namespace), query=query or None)
        rv = (out.get("metadata") or {}).get("resourceVersion", "")
        return out.get("items", []), rv

    def watch(self, gvr, namespace=None, label_selector=None,
              resource_version=None, stop=None, field_selector=None):
        """Streaming watch over a raw socket with our own HTTP/chunked
        parser: connection establishment uses the full client timeout; the
        stream is read with a 1s socket timeout so `stop` is noticed
        promptly, and because ALL partial data lives in our own buffer a
        timed-out read can never desync the chunked framing (which it can
        inside http.client's buffered decoder)."""
        query = {"watch": "true", "allowWatchBookmarks": "true"}
        if label_selector:
            query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        if resource_version:
            query["resourceVersion"] = resource_version
        parsed = urllib.parse.urlsplit(self._base)
        path = gvr.path(namespace) + "?" + urllib.parse.urlencode(query)
        port = parsed.port or (443 if parsed.scheme == "https" else 80)
        sock = socket.create_connection((parsed.hostname, port),
                                        timeout=self._timeout)
        try:
            if parsed.scheme == "https" and self._ssl is not None:
                sock = self._ssl.wrap_socket(
                    sock, server_hostname=parsed.hostname)
            auth = (f"Authorization: Bearer {self._token}\r\n"
                    if self._token else "")
            sock.sendall(
                f"GET {path} HTTP/1.1\r\n"
                f"Host: {parsed.hostname}:{port}\r\n"
                f"Accept: application/json\r\n{auth}"
                f"Connection: close\r\n\r\n".encode())

            buf = b""
            # Headers arrive within the establishment timeout.
            while b"\r\n\r\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ApiError(0, "watch connection closed during headers")
                buf += chunk
            head, _, buf = buf.partition(b"\r\n\r\n")
            status_line = head.split(b"\r\n", 1)[0].decode()
            status = int(status_line.split()[1])
            if status != 200:
                raise ApiError(status, f"watch failed: {status_line}")
            chunked = b"transfer-encoding: chunked" in head.lower()

            sock.settimeout(1.0)
            line_buf = b""  # de-chunked JSON-lines payload

            def feed(data: bytes):
                nonlocal line_buf
                line_buf += data

            chunk_state = {"need": None}  # bytes left in current chunk

            def dechunk():
                """Consume complete chunked frames from buf into line_buf."""
                nonlocal buf
                while True:
                    if chunk_state["need"] is None:
                        if b"\r\n" not in buf:
                            return
                        size_line, _, rest = buf.partition(b"\r\n")
                        try:
                            size = int(size_line.split(b";")[0].strip()
                                       or b"0", 16)
                        except ValueError:
                            raise ApiError(0, "bad chunk framing")
                        buf = rest
                        if size == 0:
                            chunk_state["need"] = -1  # EOF marker
                            return
                        chunk_state["need"] = size
                    elif chunk_state["need"] == -1:
                        return
                    else:
                        need = chunk_state["need"]
                        if len(buf) < need + 2:  # data + trailing CRLF
                            return
                        feed(buf[:need])
                        buf = buf[need + 2:]
                        chunk_state["need"] = None

            while stop is None or not stop.is_set():
                if chunked:
                    dechunk()
                else:
                    feed(buf)
                    buf = b""
                while b"\n" in line_buf:
                    line, _, line_buf = line_buf.partition(b"\n")
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    yield evt.get("type", ""), evt.get("object", {})
                if chunk_state["need"] == -1:
                    return  # server ended the stream
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    continue
                if not data:
                    return
                buf += data
        finally:
            sock.close()


# ---------------------------------------------------------------------------
# Resilient client wrapper
# ---------------------------------------------------------------------------

# HTTP statuses a well-behaved client retries (client-go's
# IsRetryableError set: throttling + server-side transient failures).
# Status 0 is our own "connection-level failure" marker.
TRANSIENT_STATUSES = frozenset({0, 429, 500, 502, 503, 504})


def is_transient(err: Exception) -> bool:
    """Would a retry plausibly succeed? Conflict/NotFound/AlreadyExists
    and other 4xx are caller-level outcomes, not network weather."""
    if isinstance(err, (NotFoundError, ConflictError, AlreadyExistsError)):
        return False
    if isinstance(err, FaultInjected):
        return True  # injected faults model transient infrastructure
    if isinstance(err, ApiError):
        return err.status in TRANSIENT_STATUSES
    return isinstance(err, (OSError, TimeoutError))


class _WatchDropped(Exception):
    """Internal: the watch stream died mid-flight; reconnect from the
    last seen resourceVersion."""


class RetryingApiClient(ApiClient):
    """Decorates any ApiClient with the reliability layer every reconcile
    loop needs (the client-go rest retry + reflector resume analog):

    - every verb retries transient errors (TRANSIENT_STATUSES, socket
      errors) with jittered exponential backoff, up to `max_attempts`;
    - ``watch`` reconnects on stream death, resuming from the last seen
      object resourceVersion so no events are lost across the gap. A
      server-side ERROR event (410 Gone above all) is passed through and
      ends the stream: resuming past it would hide a history hole, so
      the informer must relist (informer.py treats ERROR as fatal).
      Resume requires an RV to resume FROM: if the stream dies before
      any RV is known (none passed, none delivered), the wrapper ends
      the stream instead of silently reconnecting from "now" — a
      from-now reconnect would swallow whatever happened during the
      outage with no signal to the consumer.

    Mutating verbs are retried too: an ambiguous first attempt (request
    landed, response lost) then surfaces as AlreadyExists/Conflict on
    the retry — exactly what reconcile callers already tolerate.

    Consults fault sites ``k8s.api.request`` (per attempt, inside the
    retry loop) and ``k8s.watch.drop`` (per delivered event), so chaos
    schedules exercise this exact code path rather than a test double.
    """

    def __init__(self, inner: ApiClient, *, max_attempts: int = 5,
                 base_delay: float = 0.05, max_delay: float = 2.0,
                 jitter: float = 0.5, rng: Optional[random.Random] = None,
                 sleep=time.sleep):
        self._inner = inner
        self._max_attempts = max_attempts
        self._base = base_delay
        self._max_delay = max_delay
        self._jitter = jitter
        self._rng = rng or random.Random()
        # The batched prepare path fans GETs out from pool threads, so
        # verbs (and their backoff jitter) run concurrently; Random's
        # Mersenne state is not thread-safe, so draws are serialized.
        self._rng_lock = threading.Lock()
        self._sleep = sleep

    @property
    def inner(self) -> ApiClient:
        return self._inner

    def _backoff(self, attempt: int) -> float:
        d = min(self._base * (2 ** attempt), self._max_delay)
        with self._rng_lock:
            u = self._rng.random()
        return max(0.0, d * (1.0 + self._jitter * (u - 0.5)))

    def _call(self, verb: str, fn, *args, **kwargs):
        last: Optional[Exception] = None
        for attempt in range(self._max_attempts):
            try:
                FAULTS.check("k8s.api.request", verb=verb)
                return fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classified below
                if not is_transient(e):
                    raise
                last = e
            if attempt < self._max_attempts - 1:
                # No sleep after the final attempt: the outcome is
                # decided, don't tax the error path with a dead wait.
                self._sleep(self._backoff(attempt))
        assert last is not None
        raise last

    # -- verbs --------------------------------------------------------------

    def get(self, gvr, name, namespace=None):
        return self._call("get", self._inner.get, gvr, name, namespace)

    def list(self, gvr, namespace=None, label_selector=None):
        return self._call("list", self._inner.list, gvr, namespace,
                          label_selector)

    def list_with_rv(self, gvr, namespace=None, label_selector=None):
        return self._call("list", self._inner.list_with_rv, gvr, namespace,
                          label_selector)

    def create(self, gvr, obj, namespace=None):
        return self._call("create", self._inner.create, gvr, obj, namespace)

    def update(self, gvr, obj, namespace=None):
        return self._call("update", self._inner.update, gvr, obj, namespace)

    def update_status(self, gvr, obj, namespace=None):
        return self._call("update", self._inner.update_status, gvr, obj,
                          namespace)

    def patch(self, gvr, name, patch, namespace=None):
        return self._call("patch", self._inner.patch, gvr, name, patch,
                          namespace)

    def delete(self, gvr, name, namespace=None):
        return self._call("delete", self._inner.delete, gvr, name, namespace)

    # -- watch --------------------------------------------------------------

    def watch(self, gvr, namespace=None, label_selector=None,
              resource_version=None, stop=None, field_selector=None):
        rv = resource_version
        failures = 0
        while stop is None or not stop.is_set():
            gen = None
            try:
                FAULTS.check("k8s.api.request", verb="watch")
                gen = self._inner.watch(
                    gvr, namespace=namespace, label_selector=label_selector,
                    resource_version=rv, stop=stop,
                    field_selector=field_selector)
                for event_type, obj in gen:
                    if FAULTS.fires("k8s.watch.drop"):
                        raise _WatchDropped()
                    if event_type == "ERROR":
                        # 410 Gone (or any server stream error): resuming
                        # from rv would skip the trimmed gap. Surface it;
                        # the informer relists.
                        yield event_type, obj
                        return
                    failures = 0
                    new_rv = (obj.get("metadata") or {}).get(
                        "resourceVersion")
                    if new_rv:
                        rv = new_rv
                    yield event_type, obj
                # Clean server close (idle timeout): reconnect from the
                # last seen RV — the entire point of this wrapper.
            except Exception as e:  # noqa: BLE001 — classified below
                if not isinstance(e, _WatchDropped) and not is_transient(e):
                    raise
            finally:
                if gen is not None:
                    gen.close()
            if rv is None:
                # Nothing to resume from: reconnecting would start at
                # "now" and hide the gap. End the stream; the consumer's
                # relist path (the pre-wrapper contract) takes over.
                return
            failures += 1
            delay = self._backoff(min(failures - 1, self._max_attempts - 1))
            if stop is not None:
                stop.wait(delay)  # shutdown must not ride out the backoff
            else:
                self._sleep(delay)
