"""Kubernetes REST client over stdlib HTTP.

Replaces client-go's rest.Config/ClientSets (reference:
pkg/flags/kubeclient.go:33-147 builds Core/Nvidia/Resource clientsets from
either kubeconfig or in-cluster config). Objects are plain dicts
("unstructured"); typed behavior lives in the API layer.

Supports: CRUD + status subresource, JSON merge-patch, list with
label/field selectors, and streaming watch (chunked JSON lines), with
in-cluster service-account config discovery.
"""

from __future__ import annotations

import json
import os
import socket
import ssl
import threading
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, Generator, List, Optional, Tuple


@dataclass(frozen=True)
class GVR:
    """Group/version/resource coordinate; group '' = core."""
    group: str
    version: str
    plural: str
    namespaced: bool = True

    def path(self, namespace: Optional[str] = None, name: Optional[str] = None,
             subresource: Optional[str] = None) -> str:
        base = f"/api/{self.version}" if not self.group else f"/apis/{self.group}/{self.version}"
        parts = [base]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.plural)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    @property
    def key(self) -> str:
        return f"{self.group or 'core'}/{self.version}/{self.plural}"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status
        self.message = message


class NotFoundError(ApiError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class ConflictError(ApiError):
    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


class AlreadyExistsError(ApiError):
    def __init__(self, message: str = "already exists"):
        super().__init__(409, message)


def parse_label_selector(selector: str) -> List[Tuple[str, Optional[str]]]:
    """Parse 'k=v,k2,k3!=x' into [(key, value|None)] (None = exists).
    '!=' terms are represented as (key, ('!=', value))."""
    terms: List[Tuple[str, Any]] = []
    for part in filter(None, (p.strip() for p in (selector or "").split(","))):
        if "!=" in part:
            k, _, v = part.partition("!=")
            terms.append((k.strip(), ("!=", v.strip())))
        elif "=" in part:
            k, _, v = part.partition("=")
            terms.append((k.strip().rstrip("="), v.strip()))
        else:
            terms.append((part, None))
    return terms


def label_selector_matches(selector: Optional[str], labels: Dict[str, str]) -> bool:
    if not selector:
        return True
    for key, want in parse_label_selector(selector):
        if want is None:
            if key not in labels:
                return False
        elif isinstance(want, tuple):
            if labels.get(key) == want[1]:
                return False
        elif labels.get(key) != want:
            return False
    return True


class ApiClient:
    """Abstract client surface shared by HttpApiClient and FakeCluster."""

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def list(self, gvr: GVR, namespace: Optional[str] = None,
             label_selector: Optional[str] = None) -> List[Dict]:
        raise NotImplementedError

    def list_with_rv(self, gvr: GVR, namespace: Optional[str] = None,
                     label_selector: Optional[str] = None
                     ) -> Tuple[List[Dict], str]:
        """(items, collection resourceVersion). Default: no RV — watch then
        starts from 'now' (pre-RV behavior)."""
        return self.list(gvr, namespace, label_selector), ""

    def create(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def update(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def update_status(self, gvr: GVR, obj: Dict, namespace: Optional[str] = None) -> Dict:
        raise NotImplementedError

    def patch(self, gvr: GVR, name: str, patch: Dict,
              namespace: Optional[str] = None) -> Dict:
        """JSON merge-patch (RFC 7386)."""
        raise NotImplementedError

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None:
        raise NotImplementedError

    def watch(self, gvr: GVR, namespace: Optional[str] = None,
              label_selector: Optional[str] = None,
              resource_version: Optional[str] = None,
              stop: Optional[threading.Event] = None,
              ) -> Generator[Tuple[str, Dict], None, None]:
        """Yield (event_type, object): ADDED/MODIFIED/DELETED/BOOKMARK."""
        raise NotImplementedError


# ---------------------------------------------------------------------------

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
IN_CLUSTER_NS = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"


class HttpApiClient(ApiClient):
    """Stdlib-HTTP client. Config resolution mirrors KubeClientConfig
    (kubeclient.go): explicit base URL flag > in-cluster env
    (KUBERNETES_SERVICE_HOST + service account files)."""

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None, ca_file: Optional[str] = None,
                 insecure: bool = False, timeout: float = 30.0):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise ValueError(
                    "no API server URL given and not running in-cluster")
            base_url = f"https://{host}:{port}"
            if token is None and os.path.exists(IN_CLUSTER_TOKEN):
                token = open(IN_CLUSTER_TOKEN).read().strip()
            if ca_file is None and os.path.exists(IN_CLUSTER_CA):
                ca_file = IN_CLUSTER_CA
        self._base = base_url.rstrip("/")
        self._token = token
        self._timeout = timeout
        if self._base.startswith("https"):
            if insecure:
                self._ssl = ssl._create_unverified_context()  # noqa: S323
            else:
                self._ssl = ssl.create_default_context(cafile=ca_file)
        else:
            self._ssl = None

    # -- plumbing -----------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[Dict] = None,
                 query: Optional[Dict[str, str]] = None,
                 content_type: str = "application/json") -> Dict:
        url = self._base + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout,
                                        context=self._ssl) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")
            if e.code == 404:
                raise NotFoundError(msg) from e
            if e.code == 409:
                # A real apiserver returns 409 for both optimistic-concurrency
                # conflicts and create-on-existing; distinguish by the Status
                # body's reason (client-go errors.IsAlreadyExists analog) so
                # callers' `except AlreadyExistsError` works over HTTP too.
                # Only the parsed Status reason is trusted: a substring test
                # on the raw body would misclassify a genuine stale-RV
                # Conflict whose object data happens to echo the phrase
                # "already exists".
                reason = ""
                try:
                    reason = json.loads(msg).get("reason", "")
                except (ValueError, AttributeError):
                    pass
                if reason == "AlreadyExists":
                    raise AlreadyExistsError(msg) from e
                raise ConflictError(msg) from e
            raise ApiError(e.code, msg) from e

    # -- verbs --------------------------------------------------------------

    def get(self, gvr, name, namespace=None):
        return self._request("GET", gvr.path(namespace, name))

    def list(self, gvr, namespace=None, label_selector=None):
        query = {}
        if label_selector:
            query["labelSelector"] = label_selector
        out = self._request("GET", gvr.path(namespace), query=query or None)
        return out.get("items", [])

    def create(self, gvr, obj, namespace=None):
        ns = namespace or obj.get("metadata", {}).get("namespace")
        return self._request("POST", gvr.path(ns), body=obj)

    def update(self, gvr, obj, namespace=None):
        meta = obj.get("metadata", {})
        ns = namespace or meta.get("namespace")
        return self._request("PUT", gvr.path(ns, meta["name"]), body=obj)

    def update_status(self, gvr, obj, namespace=None):
        meta = obj.get("metadata", {})
        ns = namespace or meta.get("namespace")
        return self._request("PUT", gvr.path(ns, meta["name"], "status"), body=obj)

    def patch(self, gvr, name, patch, namespace=None):
        return self._request("PATCH", gvr.path(namespace, name), body=patch,
                             content_type="application/merge-patch+json")

    def delete(self, gvr, name, namespace=None):
        try:
            self._request("DELETE", gvr.path(namespace, name))
        except NotFoundError:
            pass

    def list_with_rv(self, gvr, namespace=None, label_selector=None):
        """(items, resourceVersion) — the List response's collection RV, for
        gap-free list+watch resumption."""
        query = {}
        if label_selector:
            query["labelSelector"] = label_selector
        out = self._request("GET", gvr.path(namespace), query=query or None)
        rv = (out.get("metadata") or {}).get("resourceVersion", "")
        return out.get("items", []), rv

    def watch(self, gvr, namespace=None, label_selector=None,
              resource_version=None, stop=None):
        """Streaming watch over a raw socket with our own HTTP/chunked
        parser: connection establishment uses the full client timeout; the
        stream is read with a 1s socket timeout so `stop` is noticed
        promptly, and because ALL partial data lives in our own buffer a
        timed-out read can never desync the chunked framing (which it can
        inside http.client's buffered decoder)."""
        query = {"watch": "true"}
        if label_selector:
            query["labelSelector"] = label_selector
        if resource_version:
            query["resourceVersion"] = resource_version
        parsed = urllib.parse.urlsplit(self._base)
        path = gvr.path(namespace) + "?" + urllib.parse.urlencode(query)
        port = parsed.port or (443 if parsed.scheme == "https" else 80)
        sock = socket.create_connection((parsed.hostname, port),
                                        timeout=self._timeout)
        try:
            if parsed.scheme == "https" and self._ssl is not None:
                sock = self._ssl.wrap_socket(
                    sock, server_hostname=parsed.hostname)
            auth = (f"Authorization: Bearer {self._token}\r\n"
                    if self._token else "")
            sock.sendall(
                f"GET {path} HTTP/1.1\r\n"
                f"Host: {parsed.hostname}:{port}\r\n"
                f"Accept: application/json\r\n{auth}"
                f"Connection: close\r\n\r\n".encode())

            buf = b""
            # Headers arrive within the establishment timeout.
            while b"\r\n\r\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ApiError(0, "watch connection closed during headers")
                buf += chunk
            head, _, buf = buf.partition(b"\r\n\r\n")
            status_line = head.split(b"\r\n", 1)[0].decode()
            status = int(status_line.split()[1])
            if status != 200:
                raise ApiError(status, f"watch failed: {status_line}")
            chunked = b"transfer-encoding: chunked" in head.lower()

            sock.settimeout(1.0)
            line_buf = b""  # de-chunked JSON-lines payload

            def feed(data: bytes):
                nonlocal line_buf
                line_buf += data

            chunk_state = {"need": None}  # bytes left in current chunk

            def dechunk():
                """Consume complete chunked frames from buf into line_buf."""
                nonlocal buf
                while True:
                    if chunk_state["need"] is None:
                        if b"\r\n" not in buf:
                            return
                        size_line, _, rest = buf.partition(b"\r\n")
                        try:
                            size = int(size_line.split(b";")[0].strip()
                                       or b"0", 16)
                        except ValueError:
                            raise ApiError(0, "bad chunk framing")
                        buf = rest
                        if size == 0:
                            chunk_state["need"] = -1  # EOF marker
                            return
                        chunk_state["need"] = size
                    elif chunk_state["need"] == -1:
                        return
                    else:
                        need = chunk_state["need"]
                        if len(buf) < need + 2:  # data + trailing CRLF
                            return
                        feed(buf[:need])
                        buf = buf[need + 2:]
                        chunk_state["need"] = None

            while stop is None or not stop.is_set():
                if chunked:
                    dechunk()
                else:
                    feed(buf)
                    buf = b""
                while b"\n" in line_buf:
                    line, _, line_buf = line_buf.partition(b"\n")
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    yield evt.get("type", ""), evt.get("object", {})
                if chunk_state["need"] == -1:
                    return  # server ended the stream
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    continue
                if not data:
                    return
                buf += data
        finally:
            sock.close()
