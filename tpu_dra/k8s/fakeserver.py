"""HTTP fake Kubernetes API server: FakeCluster behind real REST.

The process-level e2e tier (the reference's kind-cluster story, SURVEY
§4.2) needs the actual driver binaries (`python -m tpu_dra.*.main`) to run
as separate processes against a real apiserver endpoint. This serves a
FakeCluster over the k8s REST conventions HttpApiClient speaks:

  GET    /api/v1/... | /apis/<group>/<version>/...      (get/list)
  GET    ...?watch=true                                  (chunked stream)
  POST   collection                                      (create)
  PUT    item [/status]                                  (update)
  PATCH  item (application/merge-patch+json)             (merge patch)
  DELETE item

It is deliberately schema-less (objects are opaque dicts), matching
FakeCluster semantics: resourceVersion bumping, finalizer-aware deletion,
label selectors, namespaced + cluster-scoped resources.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from tpu_dra.k8s import resources
from tpu_dra.k8s.client import (
    AlreadyExistsError, ApiError, ConflictError, GVR, NotFoundError,
)
from tpu_dra.k8s.fake import FakeCluster

# Registry of resources the server routes (plural -> GVR); mirrors
# tpu_dra.k8s.resources. Unknown plurals 404 like a real apiserver.
KNOWN_GVRS = {
    (g.group, g.version, g.plural): g
    for g in (resources.PODS, resources.NODES, resources.EVENTS,
              resources.DAEMONSETS, resources.DEPLOYMENTS,
              resources.RESOURCECLAIMS, resources.RESOURCECLAIMTEMPLATES,
              resources.RESOURCESLICES, resources.DEVICECLASSES,
              resources.COMPUTEDOMAINS,
              resources.NAMESPACES, resources.SECRETS, resources.SERVICES,
              resources.SERVICEACCOUNTS, resources.CRDS,
              resources.CLUSTERROLES, resources.CLUSTERROLEBINDINGS,
              resources.NETWORKPOLICIES,
              resources.VALIDATINGWEBHOOKCONFIGURATIONS,
              resources.VALIDATINGADMISSIONPOLICIES,
              resources.VALIDATINGADMISSIONPOLICYBINDINGS)
}


def _parse_path(path: str) -> Optional[Tuple[GVR, Optional[str],
                                             Optional[str], Optional[str]]]:
    """Returns (gvr, namespace, name, subresource) or None."""
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api":
        if len(parts) < 2:
            return None
        group, rest = "", parts[2:]
        version = parts[1]
    elif parts[0] == "apis":
        if len(parts) < 3:
            return None
        group, version, rest = parts[1], parts[2], parts[3:]
    else:
        return None
    namespace = None
    if rest and rest[0] == "namespaces" and len(rest) >= 2:
        namespace = rest[1]
        rest = rest[2:]
    if not rest:
        return None
    plural, rest = rest[0], rest[1:]
    gvr = KNOWN_GVRS.get((group, version, plural))
    if gvr is None:
        return None
    name = rest[0] if rest else None
    subresource = rest[1] if len(rest) > 1 else None
    return gvr, namespace, name, subresource


class FakeApiServer:
    """Serves `cluster` (a FakeCluster) over HTTP; `url` is the base URL
    usable as --kube-api-url / KUBE_API_URL."""

    def __init__(self, cluster: Optional[FakeCluster] = None,
                 addr: str = "127.0.0.1", port: int = 0,
                 admission_hook=None):
        """admission_hook(gvr, obj, operation) -> Optional[str]: when set,
        runs before create/update like the real admission chain; a
        returned string denies the request (the simcluster wires a caller
        that POSTs AdmissionReviews to registered webhooks)."""
        self.cluster = cluster or FakeCluster()
        self.admission_hook = admission_hook
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send_json(self, code: int, doc: Dict):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str, reason: str = ""):
                doc = {
                    "kind": "Status", "apiVersion": "v1", "code": code,
                    "status": "Failure", "message": message}
                if reason:
                    doc["reason"] = reason
                self._send_json(code, doc)

            def _api_error(self, e: ApiError):
                # Mirror a real apiserver's Status reason so HTTP clients
                # can distinguish AlreadyExists from update conflicts
                # (client-go errors.IsAlreadyExists analog).
                reason = ""
                if isinstance(e, AlreadyExistsError):
                    reason = "AlreadyExists"
                elif isinstance(e, ConflictError):
                    reason = "Conflict"
                elif isinstance(e, NotFoundError):
                    reason = "NotFound"
                return self._error(e.status, e.message, reason)

            def _body(self) -> Dict:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length)) if length else {}

            def do_GET(self):  # noqa: N802
                url = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(url.query)
                parsed = _parse_path(url.path)
                if parsed is None:
                    return self._error(404, f"unknown path {url.path}")
                gvr, ns, name, _sub = parsed
                try:
                    if name:
                        return self._send_json(
                            200, outer.cluster.get(gvr, name, ns))
                    selector = (query.get("labelSelector") or [None])[0]
                    if (query.get("watch") or ["false"])[0] == "true":
                        rv = (query.get("resourceVersion") or [None])[0]
                        fsel = (query.get("fieldSelector") or [None])[0]
                        return self._watch(gvr, ns, selector, rv, fsel)
                    items, rv = outer.cluster.list_with_rv(
                        gvr, namespace=ns, label_selector=selector)
                    return self._send_json(200, {
                        "kind": "List", "apiVersion": "v1",
                        "metadata": {"resourceVersion": rv},
                        "items": items})
                except NotFoundError as e:
                    return self._error(404, str(e))

            def _watch(self, gvr, ns, selector, resource_version=None,
                       field_selector=None):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def write_chunk(data: bytes):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")
                    self.wfile.flush()

                try:
                    for event_type, obj in outer.cluster.watch(
                            gvr, namespace=ns, label_selector=selector,
                            resource_version=resource_version,
                            stop=outer._stop,
                            field_selector=field_selector):
                        line = json.dumps({"type": event_type,
                                           "object": obj}) + "\n"
                        write_chunk(line.encode())
                except (BrokenPipeError, ConnectionResetError):
                    return

            def _admission_denial(self, gvr, obj, operation):
                """Runs the admission chain; returns a denial message or
                None (the shared seam for CREATE/UPDATE/PATCH-as-UPDATE)."""
                if outer.admission_hook is None:
                    return None
                return outer.admission_hook(gvr, obj, operation)

            def _deny(self, message: str):
                # The hook supplies the full apiserver-format message
                # ('admission webhook "<name>" denied the request: ...').
                return self._error(400, message, reason="Invalid")

            def do_POST(self):  # noqa: N802
                parsed = _parse_path(urllib.parse.urlparse(self.path).path)
                if parsed is None:
                    return self._error(404, "unknown path")
                gvr, ns, _name, _sub = parsed
                try:
                    body = self._body()
                    deny = self._admission_denial(gvr, body, "CREATE")
                    if deny:
                        return self._deny(deny)
                    created = outer.cluster.create(gvr, body, namespace=ns)
                    return self._send_json(201, created)
                except ApiError as e:
                    return self._api_error(e)

            def do_PUT(self):  # noqa: N802
                parsed = _parse_path(urllib.parse.urlparse(self.path).path)
                if parsed is None:
                    return self._error(404, "unknown path")
                gvr, ns, _name, sub = parsed
                try:
                    body = self._body()
                    if sub == "status":
                        out = outer.cluster.update_status(gvr, body,
                                                          namespace=ns)
                    else:
                        deny = self._admission_denial(gvr, body, "UPDATE")
                        if deny:
                            return self._deny(deny)
                        out = outer.cluster.update(gvr, body, namespace=ns)
                    return self._send_json(200, out)
                except ApiError as e:
                    return self._api_error(e)

            def do_PATCH(self):  # noqa: N802
                parsed = _parse_path(urllib.parse.urlparse(self.path).path)
                if parsed is None or parsed[2] is None:
                    return self._error(404, "unknown path")
                gvr, ns, name, _sub = parsed
                try:
                    patch = self._body()
                    if outer.admission_hook is not None:
                        # Admission sees the POST-patch object, like the
                        # real apiserver (PATCH is an UPDATE there).
                        # cluster.get already returns a copy.
                        from tpu_dra.k8s.fake import _merge_patch
                        merged = _merge_patch(
                            outer.cluster.get(gvr, name, ns), patch)
                        deny = self._admission_denial(gvr, merged, "UPDATE")
                        if deny:
                            return self._deny(deny)
                    out = outer.cluster.patch(gvr, name, patch,
                                              namespace=ns)
                    return self._send_json(200, out)
                except ApiError as e:
                    return self._api_error(e)

            def do_DELETE(self):  # noqa: N802
                parsed = _parse_path(urllib.parse.urlparse(self.path).path)
                if parsed is None or parsed[2] is None:
                    return self._error(404, "unknown path")
                gvr, ns, name, _sub = parsed
                outer.cluster.delete(gvr, name, ns)
                return self._send_json(200, {"kind": "Status",
                                             "status": "Success"})

        self._stop = threading.Event()
        self._server = ThreadingHTTPServer((addr, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="fake-apiserver")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
