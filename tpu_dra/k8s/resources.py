"""Well-known GVR coordinates + object helpers.

The resource.k8s.io group is the DRA API the reference drives through
k8s.io/dynamic-resource-allocation (driver.go:73-82); apps/core are used by
the CD controller for DaemonSets/Deployments/Pods/Nodes; resource.tpu.dev
is this driver's CRD group (ComputeDomain).
"""

from __future__ import annotations

import datetime
from typing import Dict, Optional

from tpu_dra.k8s.client import GVR

PODS = GVR("", "v1", "pods")
NODES = GVR("", "v1", "nodes", namespaced=False)
EVENTS = GVR("", "v1", "events")
DAEMONSETS = GVR("apps", "v1", "daemonsets")
DEPLOYMENTS = GVR("apps", "v1", "deployments")

RESOURCECLAIMS = GVR("resource.k8s.io", "v1", "resourceclaims")
RESOURCECLAIMTEMPLATES = GVR("resource.k8s.io", "v1", "resourceclaimtemplates")
RESOURCESLICES = GVR("resource.k8s.io", "v1", "resourceslices", namespaced=False)
DEVICECLASSES = GVR("resource.k8s.io", "v1", "deviceclasses", namespaced=False)

COMPUTEDOMAINS = GVR("resource.tpu.dev", "v1beta1", "computedomains")

# coordination.k8s.io Leases back the HA scheduler's leader election
# (active-standby failover, SURVEY §22): the elector CASes holder/renew
# fields under the apiserver's resourceVersion conflict semantics.
LEASES = GVR("coordination.k8s.io", "v1", "leases")

# Kinds the driver itself never reads but the deployment manifests carry;
# registered so the fake apiserver can store a full chart install
# (simcluster tier).
NAMESPACES = GVR("", "v1", "namespaces", namespaced=False)
SECRETS = GVR("", "v1", "secrets")
SERVICES = GVR("", "v1", "services")
SERVICEACCOUNTS = GVR("", "v1", "serviceaccounts")
CRDS = GVR("apiextensions.k8s.io", "v1", "customresourcedefinitions",
           namespaced=False)
CLUSTERROLES = GVR("rbac.authorization.k8s.io", "v1", "clusterroles",
                   namespaced=False)
CLUSTERROLEBINDINGS = GVR("rbac.authorization.k8s.io", "v1",
                          "clusterrolebindings", namespaced=False)
NETWORKPOLICIES = GVR("networking.k8s.io", "v1", "networkpolicies")
VALIDATINGWEBHOOKCONFIGURATIONS = GVR(
    "admissionregistration.k8s.io", "v1",
    "validatingwebhookconfigurations", namespaced=False)
VALIDATINGADMISSIONPOLICIES = GVR(
    "admissionregistration.k8s.io", "v1",
    "validatingadmissionpolicies", namespaced=False)
VALIDATINGADMISSIONPOLICYBINDINGS = GVR(
    "admissionregistration.k8s.io", "v1",
    "validatingadmissionpolicybindings", namespaced=False)


def new_object_meta(name: str, namespace: Optional[str] = None,
                    labels: Optional[Dict[str, str]] = None,
                    annotations: Optional[Dict[str, str]] = None,
                    owner: Optional[Dict] = None) -> Dict:
    meta: Dict = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    if owner:
        meta["ownerReferences"] = [owner]
    return meta


def owner_reference(obj: Dict, controller: bool = True,
                    block_owner_deletion: bool = True) -> Dict:
    meta = obj["metadata"]
    return {
        "apiVersion": obj.get("apiVersion", ""),
        "kind": obj.get("kind", ""),
        "name": meta["name"],
        "uid": meta.get("uid", ""),
        "controller": controller,
        "blockOwnerDeletion": block_owner_deletion,
    }


def now_rfc3339() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")
