"""In-memory fake Kubernetes API server.

The analog of the reference's generated fake clientset
(pkg/nvidia.com/clientset/versioned/fake) — but covering every group the
driver touches, with watch streams, resourceVersion bumping, finalizer-aware
deletion, and optimistic-concurrency conflicts, so controller logic can be
tested against realistic apiserver semantics without a cluster.
"""

from __future__ import annotations

import datetime
import itertools
import os
import queue
import threading
from typing import Dict, Generator, List, Optional, Tuple

from tpu_dra.k8s.client import (
    AlreadyExistsError, ApiClient, ConflictError, GVR, NotFoundError,
    field_path_value, json_deepcopy, label_selector_matches,
    parse_field_selector,
)
from tpu_dra.k8s.resources import now_rfc3339

# A watch registration topic: (gvr_key, field_path|None, field_value|None).
# (gk, None, None) is the broadcast topic every plain watcher sits on;
# field-selector watchers sit on (gk, ("spec","nodeName"), "n5") and the
# emit path only walks the topics an event actually belongs to — a
# node-scoped watcher is never even iterated for another node's events.
_Topic = Tuple[str, Optional[Tuple[str, ...]], Optional[str]]


class _Watcher:
    """One watch stream: a BOUNDED queue of (type, obj) items. The fake
    apiserver never blocks its (lock-holding) emit path on a slow
    consumer — a full queue marks the stream overflowed, remaining
    buffered events drain, then the stream ends with 410 so the consumer
    relists (the real apiserver's too-slow-watcher behavior)."""

    __slots__ = ("gvr_key", "namespace", "selector", "topic", "events",
                 "closed", "overflowed")

    def __init__(self, gvr_key: str, namespace: Optional[str],
                 selector: Optional[str], topic: _Topic, cap: int):
        self.gvr_key = gvr_key
        self.namespace = namespace
        self.selector = selector
        self.topic = topic
        self.events: "queue.Queue[Tuple[str, Dict]]" = queue.Queue(maxsize=cap)
        self.closed = False
        self.overflowed = False

    def offer(self, item: Tuple[str, Dict]) -> bool:
        if self.overflowed:
            return False
        try:
            self.events.put_nowait(item)
            return True
        except queue.Full:
            self.overflowed = True
            return False


class FakeCluster(ApiClient):
    """Thread-safe in-memory object store implementing the ApiClient surface."""

    # Bounded event log for resourceVersion replay (closes the LIST->WATCH
    # gap a real apiserver closes the same way).
    EVENT_LOG_CAP = 4096
    # Per-watcher queue bound: past this, the stream is declared too slow
    # and ended with 410 (drain-then-error) so the consumer relists.
    WATCH_QUEUE_CAP = 4096

    def __init__(self):
        self._lock = threading.RLock()
        # uid source: a per-cluster random tag + counter. uuid.uuid4 was
        # one getrandom syscall per created object — a large slice of
        # fake-apiserver wall at churn scale for randomness nothing
        # needs; uniqueness per cluster instance is the whole contract.
        self._uid_tag = os.urandom(4).hex()
        self._uid_seq = itertools.count(1)
        # (gvr.key, namespace or "") -> name -> object
        self._store: Dict[Tuple[str, str], Dict[str, Dict]] = {}
        self._rv = itertools.count(1)
        self._last_rv = 0
        self._watchers: List[_Watcher] = []
        # topic -> watchers. Emit walks only the topics an event belongs
        # to (broadcast + one per registered field path with a value on
        # the object), so fan-out cost scales with MATCHING watchers, not
        # total watchers — the difference between O(1) and O(10k) per
        # event once every simulated node runs its own scoped watch.
        self._watch_index: Dict[_Topic, List[_Watcher]] = {}
        # gvr_key -> field paths with at least one historical registration
        # (bounded: the schema-level universe of watched paths). Emit
        # extracts these paths once per event to compute its topics.
        self._field_paths: Dict[str, set] = {}
        # (gvr_key, path) -> global _trimmed_rv when the path was FIRST
        # registered. Before that point no per-topic watermarks exist for
        # the path, so a resume from older history must 410 (we cannot
        # prove the trimmed range held no matching events).
        self._field_path_since: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        # [(rv, gvr_key, ns, event_type, obj, topics)] — replayed for
        # watches that resume from an older resourceVersion. Topics are
        # precomputed at emit so trim-time watermark upkeep is a lookup.
        self._events: List[Tuple[int, str, str, str, Dict, List[_Topic]]] = []
        # Highest RV dropped from the bounded log: a resume from at or
        # below it has a hole and must get 410 Gone, not a silent skip —
        # UNLESS the watch is field-scoped and the per-topic watermark
        # below proves no matching event was in the hole (bookmark
        # semantics: dead ranges are skippable when provably irrelevant).
        self._trimmed_rv = 0
        # topic -> highest rv of a trimmed event that carried this topic.
        self._topic_trimmed: Dict[_Topic, int] = {}
        # Hooks for tests: callables (verb, gvr, obj) -> obj|None run before
        # the verb; raising simulates apiserver errors (webhook analog).
        self.reactors = []

    # -- helpers ------------------------------------------------------------

    def _ns_key(self, gvr: GVR, namespace: Optional[str], obj: Optional[Dict] = None
                ) -> Tuple[str, str]:
        ns = ""
        if gvr.namespaced:
            ns = namespace or (obj or {}).get("metadata", {}).get("namespace") or "default"
        return (gvr.key, ns)

    def _bump(self, obj: Dict) -> None:
        self._last_rv = next(self._rv)
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._last_rv)

    def _emit(self, gvr: GVR, ns: str, event_type: str, obj: Dict) -> None:
        # ONE frozen snapshot per event (single-encode), shared by the
        # replay log and every watcher queue (multi-enqueue) — events are
        # read-only by contract; the informer layer copies before handing
        # objects to mutating consumers. Fan-out walks the topic index,
        # not the watcher list: the broadcast topic plus one topic per
        # registered field path the object has a value at. 10k node-scoped
        # watchers cost this loop exactly one queue append (the one
        # matching node), not 10k filter evaluations.
        snapshot = json_deepcopy(obj)
        rv = int(obj.get("metadata", {}).get("resourceVersion", "0") or 0)
        gk = gvr.key
        topics: List[_Topic] = [(gk, None, None)]
        for path in self._field_paths.get(gk, ()):
            val = field_path_value(snapshot, path)
            if val is not None:
                topics.append((gk, path, val))
        self._events.append((rv, gk, ns, event_type, snapshot, topics))
        if len(self._events) > self.EVENT_LOG_CAP:
            cut = len(self._events) - self.EVENT_LOG_CAP
            self._trimmed_rv = max(self._trimmed_rv, self._events[cut - 1][0])
            for ev in self._events[:cut]:
                for t in ev[5]:
                    if t[1] is not None and ev[0] > self._topic_trimmed.get(t, 0):
                        self._topic_trimmed[t] = ev[0]
            del self._events[:cut]
        labels = snapshot.get("metadata", {}).get("labels", {}) or {}
        item = (event_type, snapshot)
        for t in topics:
            for w in self._watch_index.get(t, ()):
                if w.closed:
                    continue
                if w.namespace and gvr.namespaced and w.namespace != ns:
                    continue
                if w.selector and not label_selector_matches(w.selector, labels):
                    continue
                w.offer(item)

    def _run_reactors(self, verb: str, gvr: GVR, obj: Optional[Dict]):
        for r in self.reactors:
            out = r(verb, gvr, obj)
            if out is not None:
                obj = out
        return obj

    # -- verbs --------------------------------------------------------------

    def get(self, gvr, name, namespace=None):
        with self._lock:
            objs = self._store.get(self._ns_key(gvr, namespace), {})
            if name not in objs:
                raise NotFoundError(f"{gvr.plural}/{name}")
            return json_deepcopy(objs[name])

    def list(self, gvr, namespace=None, label_selector=None):
        with self._lock:
            if gvr.namespaced and namespace is None:
                buckets = [v for (k, _ns), v in self._store.items() if k == gvr.key]
            else:
                buckets = [self._store.get(self._ns_key(gvr, namespace), {})]
            out = []
            for bucket in buckets:
                for obj in bucket.values():
                    labels = obj.get("metadata", {}).get("labels", {}) or {}
                    if label_selector_matches(label_selector, labels):
                        out.append(json_deepcopy(obj))
            out.sort(key=lambda o: (o["metadata"].get("namespace", ""),
                                    o["metadata"]["name"]))
            return out

    def create(self, gvr, obj, namespace=None):
        with self._lock:
            obj = json_deepcopy(obj)
            obj = self._run_reactors("create", gvr, obj)
            meta = obj.setdefault("metadata", {})
            # generateName support (ResourceClaims from templates use it).
            if "name" not in meta and meta.get("generateName"):
                meta["name"] = (meta["generateName"]
                                + f"{next(self._uid_seq):06x}")
            key = self._ns_key(gvr, namespace, obj)
            if gvr.namespaced:
                meta.setdefault("namespace", key[1])
            bucket = self._store.setdefault(key, {})
            if meta["name"] in bucket:
                raise AlreadyExistsError(f"{gvr.plural}/{meta['name']}")
            meta.setdefault(
                "uid", f"uid-{self._uid_tag}-{next(self._uid_seq)}")
            meta.setdefault("creationTimestamp", now_rfc3339())
            self._bump(obj)
            bucket[meta["name"]] = obj
            self._emit(gvr, key[1], "ADDED", obj)
            return json_deepcopy(obj)

    def _update_impl(self, gvr, obj, namespace, subresource: Optional[str]):
        with self._lock:
            obj = json_deepcopy(obj)
            obj = self._run_reactors("update", gvr, obj)
            meta = obj.get("metadata", {})
            key = self._ns_key(gvr, namespace, obj)
            bucket = self._store.get(key, {})
            name = meta.get("name", "")
            if name not in bucket:
                raise NotFoundError(f"{gvr.plural}/{name}")
            current = bucket[name]
            want_rv = meta.get("resourceVersion")
            if want_rv and want_rv != current["metadata"].get("resourceVersion"):
                raise ConflictError(
                    f"{gvr.plural}/{name}: resourceVersion mismatch")
            if subresource == "status":
                merged = json_deepcopy(current)
                merged["status"] = json_deepcopy(obj.get("status"))
                # Kubernetes permits metadata (labels/annotations)
                # changes through the status subresource — the
                # scheduler stamps the claim's traceparent annotation
                # in the SAME write as the allocation (SURVEY §19), so
                # the fake must not silently strip it.
                for mkey in ("labels", "annotations"):
                    if mkey in meta:
                        merged["metadata"][mkey] = json_deepcopy(
                            meta[mkey])
            else:
                merged = obj
                # status subresource: spec-updates do not touch status
                if "status" in current and gvr.key in _STATUS_SUBRESOURCE:
                    merged["status"] = json_deepcopy(current["status"])
                # preserve immutable server-side fields
                merged["metadata"]["uid"] = current["metadata"].get("uid")
                merged["metadata"].setdefault(
                    "creationTimestamp", current["metadata"].get("creationTimestamp"))
                if "deletionTimestamp" in current["metadata"]:
                    merged["metadata"]["deletionTimestamp"] = \
                        current["metadata"]["deletionTimestamp"]
            self._bump(merged)
            bucket[name] = merged
            self._emit(gvr, key[1], "MODIFIED", merged)
            # Finalizer-aware GC: a deleting object whose finalizers emptied
            # out is removed (apiserver behavior the CD teardown relies on).
            if (merged["metadata"].get("deletionTimestamp")
                    and not merged["metadata"].get("finalizers")):
                del bucket[name]
                # Fresh RV for the DELETED event: reusing the MODIFIED
                # event's RV would let a watch resuming from it skip the
                # deletion entirely (`rv <= since` in the replay path) —
                # an event-loss hole an incremental cache index never
                # recovers from without a full resync.
                self._bump(merged)
                self._emit(gvr, key[1], "DELETED", merged)
            return json_deepcopy(merged)

    def update(self, gvr, obj, namespace=None):
        return self._update_impl(gvr, obj, namespace, None)

    def update_status(self, gvr, obj, namespace=None):
        return self._update_impl(gvr, obj, namespace, "status")

    def patch(self, gvr, name, patch, namespace=None):
        with self._lock:
            current = self.get(gvr, name, namespace)
            merged = _merge_patch(current, patch)
            merged["metadata"]["name"] = name
            return self._update_impl(gvr, merged, namespace, None)

    def delete(self, gvr, name, namespace=None):
        with self._lock:
            self._run_reactors("delete", gvr, None)
            key = self._ns_key(gvr, namespace)
            bucket = self._store.get(key, {})
            if name not in bucket:
                return
            obj = bucket[name]
            finalizers = obj.get("metadata", {}).get("finalizers") or []
            if finalizers:
                if not obj["metadata"].get("deletionTimestamp"):
                    obj["metadata"]["deletionTimestamp"] = now_rfc3339()
                    self._bump(obj)
                    self._emit(gvr, key[1], "MODIFIED", obj)
                return
            del bucket[name]
            # Deletion advances the RV so a replay from the pre-delete list
            # RV includes this DELETED event.
            self._bump(obj)
            self._emit(gvr, key[1], "DELETED", obj)

    def list_with_rv(self, gvr, namespace=None, label_selector=None):
        with self._lock:
            return (self.list(gvr, namespace, label_selector),
                    str(self._last_rv))

    @staticmethod
    def _gone_status(message: str) -> Tuple[str, Dict]:
        return ("ERROR", {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": 410, "reason": "Expired", "message": message})

    def watch(self, gvr, namespace=None, label_selector=None,
              resource_version=None, stop=None, field_selector=None,
              ) -> Generator[Tuple[str, Dict], None, None]:
        """Watch with indexed registration and bookmark semantics.

        A ``field_selector`` ('spec.nodeName=n5') registers the watcher
        on a single topic: the emit path never iterates it for events
        whose object has a different value at that path. This suits
        set-once fields (a pod's nodeName binds once, kubelet-style):
        an object CREATED without the field only hits the broadcast
        topic, the MODIFIED that sets it and every later event reach the
        scoped watcher, and no DELETED is synthesized on a field-value
        transition away — scoped consumers of mutable fields must use a
        broadcast watch and filter client-side.

        Resume (``resource_version``) replays retained history after
        that RV. A broadcast resume below the trim point gets 410 Gone;
        a field-scoped resume additionally consults the per-topic trim
        watermark, so it survives log compaction as long as no MATCHING
        event was trimmed — dead ranges full of other nodes' churn are
        skipped, not relisted. Field-scoped streams open with a BOOKMARK
        carrying the current RV so the client's resume point advances
        past dead history even when no real event matches.
        """
        gk = gvr.key
        ns_scope = namespace if gvr.namespaced else None
        field = None
        if field_selector:
            field = parse_field_selector(field_selector)
        topic: _Topic = (gk, field[0], field[1]) if field else (gk, None, None)
        gone: Optional[str] = None
        w = _Watcher(gk, ns_scope, label_selector, topic,
                     self.WATCH_QUEUE_CAP)
        with self._lock:
            if field:
                # Register the path for emit-side topic extraction. The
                # watermark floor is the trim point at FIRST registration:
                # older history never had this topic indexed.
                self._field_paths.setdefault(gk, set()).add(field[0])
                self._field_path_since.setdefault(
                    (gk, field[0]), self._trimmed_rv)
            # Atomically: replay events after resource_version, then go
            # live — no gap in which an event can be lost.
            if resource_version:
                try:
                    since = int(resource_version)
                except ValueError:
                    since = 0
                if field:
                    dead = max(
                        self._topic_trimmed.get(topic, 0),
                        self._field_path_since[(gk, field[0])])
                else:
                    dead = self._trimmed_rv
                if since < dead:
                    # Events between `since` and the oldest retained (or
                    # provably-relevant) RV are unrecoverable. Real
                    # apiserver semantics: 410 Gone, client relists.
                    gone = (f"too old resource version: "
                            f"{resource_version} ({dead})")
                else:
                    for rv, gvr_key, ns, event_type, obj, _t in self._events:
                        if rv <= since or gvr_key != gk:
                            continue
                        if ns_scope and gvr.namespaced and ns_scope != ns:
                            continue
                        if field and field_path_value(obj, field[0]) != field[1]:
                            continue
                        labels = obj.get("metadata", {}).get("labels", {}) or {}
                        if not label_selector_matches(label_selector, labels):
                            continue
                        # Stored snapshots are frozen (read-only contract)
                        # — replay shares them, same as live fan-out.
                        w.offer((event_type, obj))
            if gone is None:
                self._watchers.append(w)
                self._watch_index.setdefault(topic, []).append(w)
                if field:
                    # Start-of-stream bookmark (field-scoped streams
                    # only — broadcast consumers predate bookmarks and
                    # don't need them): advances the client's resume RV
                    # to "now" so an idle scoped watcher can later
                    # resume across ranges trimmed while it was away.
                    w.offer(("BOOKMARK", {"metadata": {
                        "resourceVersion": str(self._last_rv)}}))
        if gone is not None:
            yield self._gone_status(gone)
            return
        try:
            while stop is None or not stop.is_set():
                try:
                    yield w.events.get(timeout=0.1)
                except queue.Empty:
                    if w.overflowed:
                        # Buffered events all drained; the stream lost
                        # later ones. End it the way the real apiserver
                        # ends a too-slow watch: the client relists.
                        yield self._gone_status(
                            "watch queue overflow: events dropped, relist")
                        return
                    continue
        finally:
            w.closed = True
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)
                peers = self._watch_index.get(topic)
                if peers is not None:
                    try:
                        peers.remove(w)
                    except ValueError:
                        pass
                    if not peers:
                        del self._watch_index[topic]

    # -- test conveniences --------------------------------------------------

    def wait_for(self, predicate, timeout: float = 5.0, interval: float = 0.02) -> bool:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(interval)
        return predicate()


# GVR keys whose status is a separate subresource (spec updates don't clobber
# status). Our CRD declares the status subresource like the reference's.
_STATUS_SUBRESOURCE = {
    "resource.tpu.dev/v1beta1/computedomains",
    "apps/v1/daemonsets",
    "apps/v1/deployments",
    "core/v1/pods",
    "core/v1/nodes",
    "resource.k8s.io/v1/resourceclaims",
}


# ---------------------------------------------------------------------------
# coordination.k8s.io/v1 Lease (HA scheduler leader election, SURVEY §22)
# ---------------------------------------------------------------------------
# The Lease rides the generic store: what makes it usable for election
# is that _update_impl's resourceVersion conflict gives electors a real
# compare-and-swap — two standbys racing a takeover CAS the same RV and
# exactly one wins. `spec.leaseTransitions` is the fencing generation a
# leader stamps into its claim-status writes (infra/leaderelect.py).

_LEASE_MICRO_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def lease_micro_time(t: float) -> str:
    """RFC3339 MicroTime (the real Lease's acquireTime/renewTime type —
    election math needs sub-second precision a 1s timestamp loses)."""
    return datetime.datetime.fromtimestamp(
        t, datetime.timezone.utc).strftime(_LEASE_MICRO_FMT)


def parse_lease_micro_time(s: Optional[str]) -> float:
    """Inverse of lease_micro_time; 0.0 for a missing/garbled stamp (an
    unreadable renewTime reads as expired — safe for takeover, and the
    holder's own next renew rewrites it)."""
    if not s:
        return 0.0
    try:
        return datetime.datetime.strptime(
            s, _LEASE_MICRO_FMT).replace(
                tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        return 0.0


def new_lease(name: str, namespace: str, holder: str,
              lease_duration_s: float, now: float) -> Dict:
    """A coordination.k8s.io/v1 Lease held by `holder` as of `now`."""
    stamp = lease_micro_time(now)
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": lease_duration_s,
            "acquireTime": stamp,
            "renewTime": stamp,
            "leaseTransitions": 1,
        },
    }


def _merge_patch(target: Dict, patch: Dict) -> Dict:
    """RFC 7386 JSON merge-patch."""
    if not isinstance(patch, dict):
        return json_deepcopy(patch)
    out = json_deepcopy(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out
