"""Kubernetes client machinery.

Replaces what the reference pulls from client-go plus its generated
clientset/informers/listers (pkg/nvidia.com, SURVEY §2.2): a typed-enough
REST client over stdlib HTTP, list+watch informers with indexers, and an
in-memory fake API server with real watch/finalizer semantics for tests
(the fake-clientset analog).
"""

from tpu_dra.k8s.client import (  # noqa: F401
    ApiClient, ApiError, ConflictError, NotFoundError, GVR, HttpApiClient,
    RetryingApiClient, label_selector_matches,
)
from tpu_dra.k8s.resources import (  # noqa: F401
    PODS, NODES, DAEMONSETS, DEPLOYMENTS, LEASES, RESOURCECLAIMS,
    RESOURCECLAIMTEMPLATES, RESOURCESLICES, DEVICECLASSES, COMPUTEDOMAINS,
    new_object_meta,
)
from tpu_dra.k8s.fake import FakeCluster  # noqa: F401
from tpu_dra.k8s.informer import Informer, Lister  # noqa: F401
