"""List+watch informer with indexers and a mutation cache.

Replaces the generated informers/listers of pkg/nvidia.com plus the
controller patterns built on them: uid indexers (cd-controller
indexers.go:30-80), label indexers (computeDomainLabel), and the mutation
cache the DaemonSet manager uses to see its own writes
(daemonset.go mutation cache).
"""

from __future__ import annotations

import collections
import copy
import logging
import os
import random
import sys
import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry as _METRICS
from tpu_dra.k8s.client import ApiClient, GVR

log = logging.getLogger("tpu_dra.informer")

# Stream failures are invisible by design (the loop relists), which is
# exactly why they must be counted: a flapping apiserver shows up here
# long before anything user-visible degrades.
_RELISTS = _METRICS.counter(
    "tpu_dra_informer_relists_total",
    "informer list/watch stream failures that forced a relist")

# Partitioned-dispatch drops: a shard delta FIFO hit its bound (or the
# sched.watch_shard_dispatch fault fired) and a handler invocation was
# shed. The consumer's on_shard_overflow callback owns recovery (the
# scheduler marks the shard dirty and resyncs); this counter is how a
# recovery loop that's silently doing all the work gets noticed.
_SHARD_OVERFLOWS = _METRICS.counter(
    "tpu_dra_informer_shard_overflows_total",
    "partitioned informer dispatch drops (queue bound or injected fault)")


# Sentinel returned by Informer._set for writes that lost an RV race
# (see _set); watch loops skip dispatch for them.
STALE = object()


# ---------------------------------------------------------------------------
# View shadow: the runtime half of drflow R13 (SURVEY §20)
# ---------------------------------------------------------------------------
# The static escape analysis promises that zero-copy views reach only
# read-only sinks. The shadow CHECKS that promise in chaos runs:
# every view handed out (lister reads, index lookups, zero-copy event
# dispatch) is content-hashed at hand-out, keyed by the CALLER's
# source site; quiesce re-hashes the very same objects. Legitimate
# cache updates REPLACE objects wholesale (watch events build new
# dicts), so a changed hash means someone mutated the handed-out view
# in place — a drift. Drifts are chaos violations AND feed the
# observed⊆static gate (analysis --check-view-shadow): every drift
# site must be a statically R13-implicated view seed, or the static
# model under-approximates and lint fails.

class ViewShadow:
    """Bounded sampler: (object identity, hand-out site) pairs are
    recorded once with their content hash; ``verify()`` re-hashes.
    No-op unless enabled (chaos harnesses / TPU_DRA_VIEW_SHADOW=1)."""

    MAX_SAMPLES = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = os.environ.get("TPU_DRA_VIEW_SHADOW") == "1"
        self._samples: Dict[Tuple[str, int], Tuple[Dict, str, str]] = {}
        self._overflow = 0
        self._drifts: List[Dict] = []

    # -- lifecycle ----------------------------------------------------------

    def enable(self) -> bool:
        """Returns the previous enabled state (harness save/restore)."""
        prev, self.enabled = self.enabled, True
        return prev

    def restore(self, prev: bool) -> None:
        self.enabled = prev

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._drifts.clear()
            self._overflow = 0

    # -- recording ----------------------------------------------------------

    @staticmethod
    def _digest(obj) -> str:
        import hashlib
        import json as _json
        try:
            blob = _json.dumps(obj, sort_keys=True, default=repr)
        except (TypeError, ValueError):
            blob = repr(obj)
        return hashlib.sha1(blob.encode()).hexdigest()

    @staticmethod
    def _caller_site() -> str:
        """relpath:line of the first frame outside this module — the
        hand-out site, keyed the way the static analyzer keys view
        reads."""
        f = sys._getframe(2)
        own = __file__
        while f is not None and f.f_code.co_filename == own:
            f = f.f_back
        if f is None:
            return "?:0"
        path = f.f_code.co_filename.replace(os.sep, "/")
        for marker in ("tpu_dra/", "tests/", "hack/"):
            idx = path.rfind("/" + marker)
            if idx >= 0:
                return f"{path[idx + 1:]}:{f.f_lineno}"
        return f"{path.rsplit('/', 1)[-1]}:{f.f_lineno}"

    def record(self, obj) -> None:
        if not self.enabled or not isinstance(obj, dict):
            return
        site = self._caller_site()
        key = (site, id(obj))
        with self._lock:
            if key in self._samples:
                return  # keep the EARLIEST hash: maximal drift window
            if len(self._samples) >= self.MAX_SAMPLES:
                self._overflow += 1
                return
            try:
                name = meta_namespace_key(obj)
            except KeyError:
                name = "?"
            self._samples[key] = (obj, self._digest(obj), name)

    # -- verification -------------------------------------------------------

    def verify(self) -> List[Dict]:
        """Re-hash every sampled object; new drifts are recorded AND
        returned. Idempotent per drift: a site+object pair reports
        once."""
        with self._lock:
            fresh: List[Dict] = []
            for (site, _oid), (obj, h, name) in list(self._samples.items()):
                if self._digest(obj) != h:
                    fresh.append({"site": site, "key": name})
                    del self._samples[(site, _oid)]
            self._drifts.extend(fresh)
            return fresh

    def snapshot(self) -> int:
        with self._lock:
            return len(self._drifts)

    def violations_since(self, snap: int) -> List[str]:
        self.verify()
        with self._lock:
            return [
                f"zero-copy view drift: object {d['key']!r} handed out "
                f"at {d['site']} was mutated in place (SURVEY §10 "
                "ownership rule; static analog: drflow R13)"
                for d in self._drifts[snap:]]

    # -- export (the lint.sh observed⊆static seam) --------------------------

    EXPORT_ENV = "TPU_DRA_VIEW_SHADOW_EXPORT"

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Merge observed drifts into the JSON file at `path` (default
        $TPU_DRA_VIEW_SHADOW_EXPORT; None = no-op). Merging mirrors the
        lock witness: several harness runs accumulate one file. An
        EMPTY export is still written — the gate reading the file
        distinguishes 'ran drift-free' from 'never ran'."""
        import json as _json
        path = path or os.environ.get(self.EXPORT_ENV)
        if not path:
            return None
        self.verify()
        with self._lock:
            drifts = {(d["site"], d["key"]) for d in self._drifts}
        try:
            with open(path, encoding="utf-8") as fh:
                for d in _json.load(fh).get("drifts", ()):
                    drifts.add((d.get("site", "?"), d.get("key", "?")))
        except (OSError, ValueError):
            pass
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                _json.dump({"drifts": [{"site": s, "key": k}
                                       for s, k in sorted(drifts)]}, fh)
            os.replace(tmp, path)
        except OSError:
            return None  # best-effort, like the witness export
        return path


def load_drifts(path: str) -> List[Dict]:
    """Read a view-shadow export for --check-view-shadow. Raises on a
    missing/garbled file: an absent export turning the gate green would
    be the silent under-approximation the gate exists to catch."""
    import json as _json
    with open(path, encoding="utf-8") as fh:
        doc = _json.load(fh)
    if not isinstance(doc, dict) or not isinstance(doc.get("drifts"),
                                                   list):
        raise ValueError(f"{path}: not a view-shadow export")
    return list(doc["drifts"])


SHADOW = ViewShadow()


def meta_namespace_key(obj: Dict) -> str:
    meta = obj.get("metadata", {})
    ns = meta.get("namespace", "")
    return f"{ns}/{meta['name']}" if ns else meta["name"]


def uid_index(obj: Dict) -> List[str]:
    uid = obj.get("metadata", {}).get("uid")
    return [uid] if uid else []


def label_index(label: str) -> Callable[[Dict], List[str]]:
    def fn(obj: Dict) -> List[str]:
        val = (obj.get("metadata", {}).get("labels") or {}).get(label)
        return [val] if val else []
    return fn


class Lister:
    """Read access to an informer's cache (the lister analog).

    ``deep_copy=True`` (the default) hands every caller a private copy —
    safe to mutate, paid per read. Hot read-only consumers (the sim
    scheduler scans pods/claims/slices on every scheduling attempt) pass
    ``deep_copy=False`` and receive VIEWS of the live cache objects:
    the ownership rule (SURVEY §10) is that zero-copy reads are
    read-only — a caller that wants to mutate must ``copy.deepcopy`` the
    one object it writes, never the whole listing."""

    def __init__(self, store: Dict[str, Dict], lock: threading.RLock,
                 deep_copy: bool = True):
        self._store = store
        self._lock = lock
        self._deep_copy = deep_copy

    def get(self, name: str, namespace: str = "") -> Optional[Dict]:
        key = f"{namespace}/{name}" if namespace else name
        with self._lock:
            obj = self._store.get(key)
            if obj is None:
                return None
            if self._deep_copy:
                return copy.deepcopy(obj)
        SHADOW.record(obj)  # zero-copy hand-out: shadow the view
        return obj

    def list(self) -> List[Dict]:
        with self._lock:
            if self._deep_copy:
                return [copy.deepcopy(o) for o in self._store.values()]
            objs = list(self._store.values())
        if SHADOW.enabled:
            for o in objs:
                SHADOW.record(o)
        return objs


class ShardDispatcher:
    """Per-shard bounded delta FIFOs for partitioned handler dispatch.

    The partitioned informer routes each event's handler invocation to
    the shard of its partition key (crc32, the SAME function as the
    scheduler's AllocationIndex.shard_of, so informer shard i feeds
    exactly index shard i) and a dedicated worker drains each FIFO. One
    slow handler or dirty shard therefore never stalls siblings, and
    per-KEY ordering is preserved because a key's shard never changes.

    Queues are BOUNDED: ``offer`` never blocks the watch thread. A full
    shard (or the ``sched.watch_shard_dispatch`` fault) sheds the
    invocation and reports it through ``on_overflow`` — the consumer
    owns recovery (the scheduler marks the matching index shard dirty
    and schedules a resync), mirroring how the fake apiserver ends a
    too-slow watch with 410.

    ``drain_one`` is the single-step seam: the worker loop is just
    ``while running: drain_one(sid, timeout)``, and the drmc model
    checker drives the same method as explicit interleaved tasks.
    """

    def __init__(self, shards: int, cap: int = 4096,
                 on_overflow: Optional[Callable[[int, str], None]] = None,
                 name: str = "informer"):
        if shards <= 0:
            raise ValueError("shards must be positive")
        self._n = shards
        self._cap = cap
        self._on_overflow = on_overflow
        self._name = name
        self._queues = [collections.deque() for _ in range(shards)]
        self._conds = [threading.Condition() for _ in range(shards)]
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        self.overflows = 0

    @staticmethod
    def shard_of(key: str, shards: int) -> int:
        return zlib.crc32(key.encode()) % shards

    def route(self, key: str) -> int:
        return self.shard_of(key, self._n)

    @property
    def shards(self) -> int:
        return self._n

    def depth(self, sid: int) -> int:
        with self._conds[sid]:
            return len(self._queues[sid])

    # -- producer side ------------------------------------------------------

    def offer(self, sid: int, thunk: Callable[[], None]) -> bool:
        """Enqueue; returns False (after notifying on_overflow) when the
        shard FIFO is at its bound or the dispatch fault fires."""
        q = self._queues[sid]
        with self._conds[sid]:
            fired = FAULTS.fires("sched.watch_shard_dispatch")
            if not fired and len(q) < self._cap:
                q.append(thunk)
                self._conds[sid].notify()
                return True
            self.overflows += 1
        _SHARD_OVERFLOWS.inc()
        # Outside the shard condition: the consumer's recovery callback
        # may take its own (index) locks.
        self._shard_overflow(sid, "fault" if fired else "full")
        return False

    def _shard_overflow(self, sid: int, reason: str) -> None:
        """The declared degradation of sched.watch_shard_dispatch: shed
        the delta, hand the shard id to the consumer's recovery hook."""
        log.debug("%s dispatcher shard %d overflow (%s)",
                  self._name, sid, reason)
        if self._on_overflow is not None:
            try:
                self._on_overflow(sid, reason)
            except Exception:  # noqa: BLE001 — recovery hook must not kill the watch
                import traceback
                traceback.print_exc()

    # -- consumer side ------------------------------------------------------

    def drain_one(self, sid: int, timeout: Optional[float] = None) -> bool:
        """Run the shard's next thunk; False if none arrived in time."""
        q = self._queues[sid]
        with self._conds[sid]:
            if not q and timeout:
                self._conds[sid].wait(timeout)
            if not q:
                return False
            thunk = q.popleft()
        try:
            thunk()
        except Exception:  # noqa: BLE001 — a broken handler must not kill the worker
            import traceback
            traceback.print_exc()
        return True

    def flush(self, timeout: float = 30.0) -> bool:
        """Barrier: returns once every thunk offered BEFORE the call has
        run (new offers may land behind the barrier thunks; per-shard
        FIFO order makes the prefix guarantee exact)."""
        events = []
        for sid in range(self._n):
            ev = threading.Event()
            with self._conds[sid]:
                self._queues[sid].append(ev.set)
                self._conds[sid].notify()
            events.append(ev)
        ok = True
        for ev in events:
            ok = ev.wait(timeout) and ok
        return ok

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._stopped.clear()
        self._threads = [
            threading.Thread(target=self._worker, args=(sid,), daemon=True,
                             name=f"{self._name}-shard-{sid}")
            for sid in range(self._n)]
        for t in self._threads:
            t.start()

    def _worker(self, sid: int) -> None:
        while not self._stopped.is_set():
            self.drain_one(sid, timeout=0.2)

    def stop(self) -> None:
        self._stopped.set()
        for sid in range(self._n):
            with self._conds[sid]:
                self._conds[sid].notify_all()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []
        # Drain leftovers single-threaded so a stop() between offer and
        # drain doesn't strand handler work (informer stop is ordered
        # after the watch thread join — no new offers by now).
        for sid in range(self._n):
            while self.drain_one(sid):
                pass


class Informer:
    """Single-resource informer. Handlers run on the watch thread; keep them
    quick and enqueue real work to a WorkQueue (the reference's pattern).

    With ``partitions=N`` handler dispatch is instead routed through a
    ShardDispatcher: events are partitioned by ``partition_key`` (crc32
    of the key, aligned with AllocationIndex.shard_of) onto per-shard
    bounded FIFOs drained by per-shard workers. The CACHE is still
    updated on the watch thread (RV-monotonic, single writer); only the
    handler invocations are partitioned, so per-key handler order is
    preserved while one slow shard no longer stalls the rest."""

    def __init__(self, client: ApiClient, gvr: GVR,
                 namespace: Optional[str] = None,
                 label_selector: Optional[str] = None,
                 field_filter: Optional[Callable[[Dict], bool]] = None,
                 copy_on_read: bool = True,
                 copy_events: bool = True,
                 partitions: int = 0,
                 partition_key: Optional[Callable[[Dict], Optional[str]]] = None,
                 shard_queue_cap: int = 4096,
                 on_shard_overflow: Optional[Callable[[int, str], None]] = None):
        """copy_on_read=False makes the lister (and get_by_index) return
        views of the cache instead of deepcopies — for hot read-only
        consumers; see Lister. copy_events=False skips the per-dispatch
        deepcopy of handler arguments — handlers then share the cached
        object and MUST treat it as read-only.

        partitions=N routes handler dispatch through a ShardDispatcher
        of N shards keyed by ``partition_key(obj)`` (falling back to the
        object's namespace/name key when the extractor returns None), so
        objects of one partition — e.g. claims of one node pool — are
        handled strictly in order on one shard while other shards run
        free. ``on_shard_overflow(shard_id, reason)`` fires when a shard
        FIFO sheds work (bound hit or injected fault) — the consumer
        must treat the shard's derived state as dirty and resync."""
        self._client = client
        self._gvr = gvr
        self._namespace = namespace
        self._selector = label_selector
        self._field_filter = field_filter
        self._copy_on_read = copy_on_read
        self._copy_events = copy_events
        self._partition_key = partition_key
        self._dispatcher: Optional[ShardDispatcher] = None
        if partitions > 0:
            self._dispatcher = ShardDispatcher(
                partitions, cap=shard_queue_cap,
                on_overflow=on_shard_overflow,
                name=f"informer-{gvr.plural}")
        self._store: Dict[str, Dict] = {}
        self._lock = threading.RLock()
        self._indexers: Dict[str, Callable[[Dict], List[str]]] = {}
        self._indices: Dict[str, Dict[str, Dict[str, Dict]]] = {}
        self._add_handlers: List[Callable[[Dict], None]] = []
        self._update_handlers: List[Callable[[Dict, Dict], None]] = []
        self._delete_handlers: List[Callable[[Dict], None]] = []
        self._synced = threading.Event()
        self._listed_ok = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.lister = Lister(self._store, self._lock,
                             deep_copy=copy_on_read)

    # -- configuration (before start) ---------------------------------------

    def add_indexer(self, name: str, fn: Callable[[Dict], List[str]]) -> None:
        self._indexers[name] = fn
        self._indices[name] = {}

    def on_add(self, fn: Callable[[Dict], None]) -> None:
        self._add_handlers.append(fn)

    def on_update(self, fn: Callable[[Dict, Dict], None]) -> None:
        self._update_handlers.append(fn)

    def on_delete(self, fn: Callable[[Dict], None]) -> None:
        self._delete_handlers.append(fn)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._dispatcher is not None:
            self._dispatcher.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"informer-{self._gvr.plural}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            # Watch threads are daemons and notice _stop within ~1s (the
            # client's short read timeout); a tight join keeps multi-informer
            # shutdown inside a pod's termination grace period.
            self._thread.join(timeout=2)
        if self._dispatcher is not None:
            # After the watch thread: no producer left, so the
            # dispatcher's final single-threaded drain is complete.
            self._dispatcher.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- cache access -------------------------------------------------------

    def get_by_index(self, index: str, value: str) -> List[Dict]:
        with self._lock:
            objs = self._indices.get(index, {}).get(value, {}).values()
            if self._copy_on_read:
                return [copy.deepcopy(o) for o in objs]
            out = list(objs)
        if SHADOW.enabled:
            for o in out:
                SHADOW.record(o)
        return out

    def update_cache(self, obj: Dict) -> None:
        """Mutation cache: record our own write so the next read sees it
        even before the watch event lands (daemonset.go mutation cache)."""
        if self._accepts(obj):
            with self._lock:
                self._set(obj)

    # -- internals ----------------------------------------------------------

    def _accepts(self, obj: Dict) -> bool:
        return self._field_filter is None or self._field_filter(obj)

    @staticmethod
    def _rv_int(obj: Dict) -> Optional[int]:
        try:
            return int(obj.get("metadata", {}).get("resourceVersion") or 0)
        except (TypeError, ValueError):
            return None  # opaque RV: ordering unknown, accept the write

    def _set(self, obj: Dict):
        """Store obj; returns the previous object, None (new key), or
        the STALE sentinel when obj carries an OLDER resourceVersion
        than the cache — which happens when a consumer's update_cache
        (mutation-cache write) raced an already-queued watch event for
        an earlier state. Accepting that event would roll the cache (and
        any event-driven index built on it) back in time; per-object RV
        monotonicity is exactly what a real watch stream guarantees."""
        key = meta_namespace_key(obj)
        old = self._store.get(key)
        if old is not None:
            new_rv, old_rv = self._rv_int(obj), self._rv_int(old)
            if (new_rv is not None and old_rv is not None
                    and new_rv < old_rv):
                return STALE
        self._store[key] = obj
        self._reindex(key, old, obj)
        return old

    def _remove(self, obj: Dict) -> Optional[Dict]:
        key = meta_namespace_key(obj)
        old = self._store.pop(key, None)
        self._reindex(key, old, None)
        return old

    def _reindex(self, key: str, old: Optional[Dict], new: Optional[Dict]) -> None:
        for name, fn in self._indexers.items():
            idx = self._indices[name]
            if old is not None:
                for val in fn(old):
                    idx.get(val, {}).pop(key, None)
                    if val in idx and not idx[val]:
                        del idx[val]
            if new is not None:
                for val in fn(new):
                    idx.setdefault(val, {})[key] = new

    def _partition_of(self, args: Tuple) -> str:
        """Partition key for a dispatch: try the extractor newest-arg
        first (update dispatch passes (old, new) — the new object is
        authoritative, but e.g. a deallocated claim may only reveal its
        pool in the OLD one), falling back to the object key so every
        event routes deterministically even without a pool."""
        if self._partition_key is not None:
            for a in reversed(args):
                try:
                    key = self._partition_key(a)
                except Exception:  # noqa: BLE001  # drflow: swallow-ok[extractor bug degrades to name-hash routing, which is correct for any key]
                    key = None
                if key:
                    return key
        return meta_namespace_key(args[-1])

    def _dispatch(self, handlers, *args) -> None:
        if self._dispatcher is not None:
            sid = self._dispatcher.route(self._partition_of(args))
            # Shed-on-overflow is handled inside the dispatcher (the
            # on_shard_overflow hook owns recovery); nothing to do here.
            self._dispatcher.offer(
                sid, lambda: self._dispatch_now(handlers, *args))
            return
        self._dispatch_now(handlers, *args)

    def _dispatch_now(self, handlers, *args) -> None:
        if not self._copy_events and SHADOW.enabled:
            for a in args:
                SHADOW.record(a)
        for h in handlers:
            try:
                # copy_events=False: handlers share the cached object and
                # must treat it as read-only (the scheduler's handlers
                # only derive keys / index entries from it).
                h(*(copy.deepcopy(args) if self._copy_events else args))
            except Exception:  # noqa: BLE001 — a broken handler must not kill the watch
                import traceback
                traceback.print_exc()

    # Relist backoff bounds: quick first retry (a single 410 relist should
    # not stall handlers), capped so a down apiserver is not hammered.
    RELIST_BACKOFF_BASE = 0.2
    RELIST_BACKOFF_MAX = 30.0

    def _run(self) -> None:
        backoff = self.RELIST_BACKOFF_BASE
        while not self._stop.is_set():
            self._listed_ok = False
            try:
                self._list_and_watch()
            except Exception as e:  # noqa: BLE001 — relist on any stream failure
                if self._stop.is_set():
                    return
                # A successful LIST (even if the watch later died, e.g.
                # 410 relist) resets the backoff; consecutive list
                # failures grow it — an apiserver outage must not turn
                # every informer into a tight relist loop.
                if self._listed_ok:
                    backoff = self.RELIST_BACKOFF_BASE
                else:
                    backoff = min(backoff * 2, self.RELIST_BACKOFF_MAX)
                _RELISTS.inc()
                log.debug("informer %s list/watch failed (%s: %s); "
                          "relisting in <=%.1fs", self._gvr.plural,
                          type(e).__name__, e, backoff)
                self._stop.wait(backoff * (0.75 + 0.5 * random.random()))

    def _list_and_watch(self) -> None:
        # list_with_rv + resourceVersion-resumed watch closes the gap in
        # which an event between LIST and WATCH would be lost (clients
        # without RV support return "" and watch from 'now').
        objs, list_rv = self._client.list_with_rv(
            self._gvr, namespace=self._namespace,
            label_selector=self._selector)
        self._listed_ok = True
        with self._lock:
            seen = set()
            stale = set()
            for obj in objs:
                if not self._accepts(obj):
                    continue
                key = meta_namespace_key(obj)
                seen.add(key)
                if self._set(obj) is STALE:
                    stale.add(key)  # mutation-cache write outran the LIST
            for key in [k for k in self._store if k not in seen]:
                gone = self._store[key]
                self._remove(gone)
                self._dispatch(self._delete_handlers, gone)
        for obj in objs:
            if self._accepts(obj) and meta_namespace_key(obj) not in stale:
                self._dispatch(self._add_handlers, obj)
        if self._dispatcher is not None:
            # Consumers treat wait_for_sync() as "every initial add has
            # been HANDLED" (the scheduler's allocation index is built at
            # sync) — with partitioned dispatch that needs a barrier over
            # the shard FIFOs, not just the enqueue loop above.
            self._dispatcher.flush()
        self._synced.set()

        for event_type, obj in self._client.watch(
                self._gvr, namespace=self._namespace,
                label_selector=self._selector,
                resource_version=list_rv or None, stop=self._stop):
            if self._stop.is_set():
                return
            if event_type == "ERROR":
                # Checked before the field filter: the ERROR payload is a
                # Status (no metadata), which any filter would reject. 410
                # Gone or any server-side stream error: raise so _run
                # relists instead of continuing on a stream with a hole.
                raise RuntimeError(f"watch stream error: {obj}")
            if event_type == "BOOKMARK":
                # Resume-progress marker, not an object event: the
                # retrying client has already advanced its resume RV
                # from it; nothing to cache or dispatch.
                continue
            if not self._accepts(obj):
                continue
            if event_type in ("ADDED", "MODIFIED"):
                with self._lock:
                    old = self._set(obj)
                if old is STALE:
                    # An update_cache write already advanced this key
                    # past the event's RV; dispatching the older state
                    # would roll event-driven consumers back in time.
                    continue
                if old is None:
                    self._dispatch(self._add_handlers, obj)
                else:
                    self._dispatch(self._update_handlers, old, obj)
            elif event_type == "DELETED":
                with self._lock:
                    self._remove(obj)
                self._dispatch(self._delete_handlers, obj)
