"""Pallas flash-attention forward for TPU (the model's hot op).

Tiled causal attention: the [S, S] score matrix never materializes in HBM.
Grid is (batch*heads, q_blocks); each program streams K/V blocks for one
Q tile through VMEM with the online-softmax recurrence, accumulating in
fp32 while matmuls run bf16/f32 on the MXU.

Design (pallas_guide.md): blocks sized to MXU/VREG tiling (128 lanes),
`lax.fori_loop` over K/V blocks with a causal upper bound computed from the
program id (no wasted blocks above the diagonal), fp32 scratch accumulators
in VMEM, `interpret=True` path so numerics are testable on CPU.

`attend()` picks this kernel on TPU and the plain jnp reference elsewhere,
so the workload model runs everywhere and is fast where it matters.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_len: int,
                  causal: bool, sm_scale: float):
    """One Q tile vs all (needed) K/V tiles.

    Refs (VMEM): q [block_q, d]; k, v [seq_len, d]; o [block_q, d].
    """
    block_q, d = q_ref.shape
    q_block_idx = pl.program_id(1)
    q_start = q_block_idx * block_q

    q = q_ref[...].astype(jnp.float32) * sm_scale

    acc = jnp.zeros((block_q, d), jnp.float32)
    row_max = jnp.full((block_q,), NEG_INF, jnp.float32)
    denom = jnp.zeros((block_q,), jnp.float32)

    # Causal: K blocks strictly above the diagonal contribute nothing.
    num_k_blocks = seq_len // block_k
    if causal:
        last = jnp.minimum(num_k_blocks,
                           (q_start + block_q + block_k - 1) // block_k)
    else:
        last = num_k_blocks

    def body(kb, carry):
        acc, row_max, denom = carry
        k_start = kb * block_k
        k_blk = k_ref[pl.dslice(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(k_start, block_k), :].astype(jnp.float32)
        scores = q @ k_blk.T  # [block_q, block_k] on the MXU
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        blk_max = jnp.max(scores, axis=1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(scores - new_max[:, None])
        acc = acc * correction[:, None] + p @ v_blk
        denom = denom * correction + jnp.sum(p, axis=1)
        return acc, new_max, denom

    acc, row_max, denom = jax.lax.fori_loop(0, last, body,
                                            (acc, row_max, denom))
    o_ref[...] = (acc / denom[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """q, k, v: [B, S, H, D] -> [B, S, H, D]. S must divide by the blocks
    (pad upstream; the workload model uses power-of-two seq lens)."""
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} not divisible by blocks "
                         f"({block_q}, {block_k})")
    sm_scale = 1.0 / math.sqrt(d)

    # [B,S,H,D] -> [B*H, S, D]: one grid row per (batch, head).
    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(_flash_kernel, block_k=block_k, seq_len=s,
                               causal=causal, sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qb, kb, vb)
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))


def attend(q, k, v, *, causal: bool = True):
    """Dispatch: pallas kernel on TPU, jnp reference elsewhere."""
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu and q.shape[1] >= 128 and q.shape[1] % 128 == 0:
        return flash_attention(q, k, v, causal=causal)
    from tpu_dra.workloads.ringattention import reference_attention
    return reference_attention(q, k, v, causal=causal)
