"""Pallas flash attention (fwd + bwd) for TPU — the model's hot op.

Tiled causal attention: the [S, S] score matrix never materializes in HBM,
in either direction. Forward streams K/V blocks for one Q tile through VMEM
with the online-softmax recurrence and saves the per-row logsumexp; the
custom-VJP backward recomputes probabilities tile-by-tile from (q, k, lse)
— the flash-attention recompute trick — so the backward is two more tiled
kernels (dq; dk/dv) instead of an O(S^2) HBM round trip.

Design (pallas_guide.md): blocks sized to MXU/VREG tiling (128 lanes),
`lax.fori_loop` over blocks with causal bounds computed from the program id
(no wasted blocks above/below the diagonal), fp32 accumulators, matmuls on
the MXU, `interpret=True` path so numerics are testable on CPU.

`attend()` picks this kernel on TPU and the plain jnp reference elsewhere,
so the workload model runs everywhere and is fast where it matters. Causal
inputs whose length is not a lane multiple are zero-padded on the right —
exact for causal masking (padded keys sit above every real diagonal) — so
the training path (seq-1 positions after label shift) stays on the kernel.

This is the perf surface of the flagship workload (the analog of the
reference's NCCL/nvbandwidth numbers, tests/bats/test_cd_mnnvl_workload.bats).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128
ROPE_BASE = 10000.0
# Swept on v5e at the flagship shape (B8 S1024 H16 D128): grad-path time
# 128->11.9ms, 256->7.6ms, 512->8.4ms. 256 balances MXU occupancy per
# program against causal-block wastage; the jnp reference grad was 11.6ms.
#
# Measured dead end (don't redo): a transpose-free "packed" layout —
# grid (B, H, q_blocks) slicing head columns out of [B, S, H*D] directly
# instead of physically transposing to [B*H, S, D] — ran the attention
# grad 3x SLOWER on v5e (4.38 vs 1.54 ms/step): the K/V window loads
# become strided (row stride H*D elements), which defeats Mosaic's
# contiguous block copies, while XLA fuses the explicit transposes into
# neighbors nearly for free.
DEFAULT_BLOCK = 256
# Long sequences want a different shape: swept on v5e at S=8192
# (B1 H16 D128, rope, attention grad): (256,256) 20.4ms, (512,512) 11.0,
# (256,1024) 11.5, (384,1024) 13.3, (512,768) 10.6, (512,1024) 9.9ms —
# a wide K window cuts dkv grid rows (longer contiguous K streams, less
# per-program ramp) and bq=512 keeps the fwd/dq VMEM footprint under the
# 16MB scoped budget ((1024,*) OOMs with the full-seq K/V + rope tables
# resident). Short sequences keep 256 (S=1024 sweep: 128→11.9, 256→7.6,
# 512→8.4 ms).
LONG_SEQ_THRESHOLD = 4096
LONG_SEQ_BWD_BLOCKS = (512, 1024)


def default_blocks(s: int):
    """Forward (block_q, block_k) for sequence length `s`. The forward
    keeps DEFAULT_BLOCK at every length: its VMEM high-water (full-seq
    K/V + rope tables + blocks) sits near the 16MB scoped budget at long
    S, and larger fwd blocks OOM inside fused model steps."""
    del s
    return DEFAULT_BLOCK, DEFAULT_BLOCK


def default_bwd_blocks(s_eff: int):
    """Backward (block_q, block_k) for an EFFECTIVE (lane-aligned padded)
    length — where the long-seq win lives (the S=8192 sweep above is grad
    time, dominated by the two bwd kernels). The wide blocks are only
    chosen when they divide s_eff: otherwise they would force extra
    padding rows (causal) or an outright divisibility error (non-causal,
    which cannot pad) — for such lengths DEFAULT_BLOCK's smaller grid
    waste beats the wide window's win. Callers with odd local lengths
    (ring attention) pass explicit blocks instead."""
    bq, bk = LONG_SEQ_BWD_BLOCKS
    if s_eff >= LONG_SEQ_THRESHOLD and s_eff % bq == 0 and s_eff % bk == 0:
        return bq, bk
    return DEFAULT_BLOCK, DEFAULT_BLOCK


def default_platform() -> str:
    """Last-resort "auto" dispatch fallback for callers with no mesh in
    hand: what the DEFAULT jax backend is. Callers that hold a Mesh must
    pass its platform explicitly instead (a traced body cannot see its
    own devices, and the default backend is wrong for e.g. a CPU mesh on
    a TPU-equipped host)."""
    return ("tpu" if any(dev.platform == "tpu" for dev in jax.devices())
            else "cpu")


def mesh_platform(mesh) -> str:
    """The "auto"-dispatch platform of a Mesh: "tpu" only when EVERY
    device is a TPU (a mixed mesh must not pick the Mosaic kernel).
    Shared by the train-step/SP factories — the mesh-held counterpart of
    default_platform()."""
    return ("tpu" if all(dev.platform == "tpu"
                         for dev in mesh.devices.flat) else "cpu")


def rope_half(x, positions):
    """Half-split-pairing rotary embedding: plane j rotates dims
    (j, j+D/2) by positions * ROPE_BASE^(-2j/D). x: [B, S, H, D],
    positions: [B, S] (or broadcastable). fp32 math, x.dtype out.

    This is the jnp reference for the IN-KERNEL rotation below
    (_rope_tile): the kernels fuse RoPE into the attention tiles so
    roped q/k never round-trip HBM. Half-split pairing (not GPT-J-style
    even/odd interleave) because contiguous half-slices are the cheap
    shape for VMEM lane slicing; as an architecture choice the pairings
    are equally expressive, they just must match everywhere.

    Expressed as the SAME multiply-add the kernel tables use —
    ``x * cos_t + roll(x, D/2) * sinm_t`` (_rope_tables) — rather than
    slice-halves + concatenate: the two formulations are bitwise the
    same math, but the slice+concat shape is miscompiled by this
    container's XLA CPU SPMD partitioner when the head_dim axis is
    sharded (a model-parallel mesh whose 'model' extent exceeds
    n_heads spills into head_dim) — observed as multi-unit logit
    divergence in the tier-1 TP-parity tests, identical in f32, gone
    under the roll form. roll() lowers to a collective-permute-style
    reshard the partitioner handles correctly.
    """
    d = x.shape[-1]
    half = d // 2
    j = jnp.arange(d, dtype=jnp.float32) % half
    freqs = jnp.exp(j * (-2.0 * math.log(ROPE_BASE) / d))
    angles = positions[..., None, None].astype(jnp.float32) * freqs
    cos_t = jnp.cos(angles)
    # Rotation sign pattern: -sin pairs the first half with its +D/2
    # partner, +sin the second half with its -D/2 partner (the roll).
    sign = jnp.where(jnp.arange(d) < half, -1.0, 1.0)
    sinm_t = jnp.sin(angles) * sign
    xf = x.astype(jnp.float32)
    return (xf * cos_t
            + jnp.roll(xf, half, axis=-1) * sinm_t).astype(x.dtype)


def _rope_tables(s: int, d: int):
    """Full-width rope tables, computed OUTSIDE the kernels (ordinary XLA
    ops, one fused [S, D] pass) and passed in as operands: in-kernel
    transcendentals cost ~40ms/step at the flagship shape (5 sin/cos
    tiles per program, re-derived per K block), tables cost ~0.5MB VMEM.

    cos_t[p, j] = cos(theta(p, j mod D/2)); sinm_t carries the rotation's
    sign pattern (-sin on the first half, +sin on the second), so both
    halves apply as  roped = x * cos_t + roll(x, D/2) * sinm_t
    — multiply-add plus one lane rotate, no shuffle-heavy interleaving.
    The INVERSE rotation (the VJP) is the same expression with -sinm_t.
    """
    half = d // 2
    j = jnp.arange(half, dtype=jnp.float32)
    freqs = jnp.exp(j * (-2.0 * math.log(ROPE_BASE) / d))   # [half]
    ang = jnp.arange(s, dtype=jnp.float32)[:, None] * freqs  # [S, half]
    cos = jnp.cos(ang)
    sin = jnp.sin(ang)
    cos_t = jnp.concatenate([cos, cos], axis=1)
    sinm_t = jnp.concatenate([-sin, sin], axis=1)
    return cos_t, sinm_t


def _rope_apply(x, start, cos_ref, sinm_ref, *, inverse: bool = False):
    """In-kernel rope_half for a [rows, d] tile whose global row r sits at
    position start + r, using the precomputed [S, D] tables. inverse=True
    applies the transpose rotation (R(-theta)) — the VJP of the forward
    rotation, mapping accumulated dq/dk (w.r.t. ROPED q/k) back to the
    unroped inputs."""
    rows, d = x.shape
    cos = cos_ref[pl.dslice(start, rows), :]
    sinm = sinm_ref[pl.dslice(start, rows), :]
    if inverse:
        sinm = -sinm
    xf = x.astype(jnp.float32)
    rolled = jnp.roll(xf, d // 2, axis=-1)
    return (xf * cos + rolled * sinm).astype(x.dtype)


def _dot(a, b, *, trans_b: bool = False, trans_a: bool = False):
    """Matmul in the operands' own dtype (bf16 stays bf16 — the MXU's
    fast path; fp32 operands would quarter v5e throughput) with fp32
    accumulation."""
    ca = 0 if trans_a else a.ndim - 1
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(a, b, (((ca,), (cb,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int,
                seq_len: int, causal: bool, sm_scale: float, rope: bool):
    """One Q tile vs all (needed) K/V tiles.

    Refs (VMEM): q [block_q, d]; k, v [seq_len, d]; o [block_q, d];
    lse [1, block_q] fp32 — the per-row logsumexp saved for the backward.
    (lse/delta ride a [BH, 1, S] layout: Mosaic requires a block's last
    two dims to be (8k, 128m) or full-size, and (1, block_q) qualifies.)

    rope=True fuses rope_half into the tiles (positions = row index)
    using precomputed [S, D] cos/sin table refs (inserted before the
    outputs in *rest), so roped q/k exist only in VMEM — the external
    rope's HBM round trips (~9ms/step at the flagship shape) become
    multiply-adds that overlap the MXU matmuls.
    """
    if rope:
        cos_ref, sinm_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    block_q, d = q_ref.shape
    q_start = pl.program_id(1) * block_q

    q = q_ref[...]  # native dtype: scores matmul rides the bf16 MXU path
    if rope:
        q = _rope_apply(q, q_start, cos_ref, sinm_ref)

    acc = jnp.zeros((block_q, d), jnp.float32)
    row_max = jnp.full((block_q,), NEG_INF, jnp.float32)
    denom = jnp.zeros((block_q,), jnp.float32)

    # Causal: K blocks strictly above the diagonal contribute nothing,
    # and blocks strictly BELOW it (k_start+block_k-1 <= q_start) need no
    # mask at all — the iota/compare/select only runs on the O(1)
    # diagonal-straddling blocks, not the O(S) interior ones.
    num_k_blocks = seq_len // block_k
    if causal:
        last = jnp.minimum(num_k_blocks,
                           (q_start + block_q + block_k - 1) // block_k)
        split = jnp.minimum(last, q_start // block_k)
    else:
        last = num_k_blocks
        split = last

    def body(kb, carry, *, masked):
        acc, row_max, denom = carry
        k_start = kb * block_k
        k_blk = k_ref[pl.dslice(k_start, block_k), :]
        v_blk = v_ref[pl.dslice(k_start, block_k), :]
        if rope:
            k_blk = _rope_apply(k_blk, k_start, cos_ref, sinm_ref)
        scores = _dot(q, k_blk, trans_b=True) * sm_scale  # fp32 [bq, bk]
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        blk_max = jnp.max(scores, axis=1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(scores - new_max[:, None])
        acc = acc * correction[:, None] + _dot(p.astype(v_blk.dtype), v_blk)
        denom = denom * correction + jnp.sum(p, axis=1)
        return acc, new_max, denom

    carry = jax.lax.fori_loop(0, split,
                              functools.partial(body, masked=False),
                              (acc, row_max, denom))
    acc, row_max, denom = jax.lax.fori_loop(
        split, last, functools.partial(body, masked=causal), carry)
    # denom >= 1 always: causal rows include their own diagonal (masking
    # uses a finite sentinel, so even a fully-masked row would sum
    # exp(0) terms), and entirely-future blocks never reach the kernel
    # (ring attention routes them around it, ringattention.future_fn).
    o_ref[...] = (acc / denom[:, None]).astype(o_ref.dtype)
    lse_ref[0, :] = row_max + jnp.log(denom)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dlse_ref, *rest, block_k: int, seq_len: int,
                   causal: bool, sm_scale: float, rope: bool):
    """dQ for one Q tile: stream K/V tiles, recompute P from (q, k, lse).

    dS_ij = P_ij * (dO_i . V_j - delta_i + dlse_i);
    dQ_i = sm_scale * sum_j dS_ij K_j, where delta_i = dO_i . O_i
    (precomputed outside, one fused reduce) and dlse is the cotangent of
    the exposed logsumexp output (d lse_i / d s_ij = P_ij — this is what
    lets ring attention merge per-step partials differentiably).

    With rope: q/k are re-roped in-tile (residuals store the UNroped
    inputs), the accumulated gradient is w.r.t. roped q, and the chain
    rule through the rotation is one inverse rotation at the end
    (d/dq = R(pos)^T dq_roped = R(-pos) dq_roped).
    """
    if rope:
        cos_ref, sinm_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    block_q, d = q_ref.shape
    q_start = pl.program_id(1) * block_q

    q = q_ref[...]
    if rope:
        q = _rope_apply(q, q_start, cos_ref, sinm_ref)
    do = do_ref[...]
    lse = lse_ref[0, :].astype(jnp.float32)
    # Fold the two per-row linear terms once, outside the K loop.
    corr = (dlse_ref[0, :].astype(jnp.float32)
            - delta_ref[0, :].astype(jnp.float32))

    num_k_blocks = seq_len // block_k
    if causal:
        last = jnp.minimum(num_k_blocks,
                           (q_start + block_q + block_k - 1) // block_k)
        # Interior blocks (fully below the diagonal) skip the mask work.
        split = jnp.minimum(last, q_start // block_k)
    else:
        last = num_k_blocks
        split = last

    def body(kb, acc, *, masked):
        k_start = kb * block_k
        k_blk = k_ref[pl.dslice(k_start, block_k), :]
        v_blk = v_ref[pl.dslice(k_start, block_k), :]
        if rope:
            k_blk = _rope_apply(k_blk, k_start, cos_ref, sinm_ref)
        scores = _dot(q, k_blk, trans_b=True) * sm_scale
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        p = jnp.exp(scores - lse[:, None])  # masked entries exp(-inf) = 0
        dp = _dot(do, v_blk, trans_b=True)
        ds = p * (dp + corr[:, None])
        return acc + _dot(ds.astype(k_blk.dtype), k_blk)

    acc = jax.lax.fori_loop(0, split, functools.partial(body, masked=False),
                            jnp.zeros((block_q, d), jnp.float32))
    acc = jax.lax.fori_loop(split, last,
                            functools.partial(body, masked=causal), acc)
    acc = acc * sm_scale
    if rope:
        acc = _rope_apply(acc, q_start, cos_ref, sinm_ref, inverse=True)
    dq_ref[...] = acc.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dlse_ref, *rest, block_q: int,
                    seq_len: int, causal: bool, sm_scale: float,
                    rope: bool):
    """dK/dV for one K/V tile: stream Q/dO tiles from the diagonal down.

    dV_j = sum_i P_ij dO_i;  dK_j = sm_scale * sum_i dS_ij Q_i,
    with dS_ij = P_ij * (dP_ij - delta_i + dlse_i) as in _bwd_dq_kernel.
    With rope, dK is inverse-rotated at the end (see _bwd_dq_kernel);
    dV is untouched (v is never roped).
    """
    if rope:
        cos_ref, sinm_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    block_k, d = k_ref.shape
    k_start = pl.program_id(1) * block_k

    k_t = k_ref[...]
    if rope:
        k_t = _rope_apply(k_t, k_start, cos_ref, sinm_ref)
    v_t = v_ref[...]

    num_q_blocks = seq_len // block_q
    # Causal: Q blocks strictly left of this K tile's diagonal see none of
    # it; Q blocks strictly BELOW it (q_start >= k_start + block_k - 1)
    # see all of it and need no mask — the iota/select only runs on the
    # O(1) diagonal-straddling blocks.
    if causal:
        first = k_start // block_q
        split = jnp.minimum(
            num_q_blocks,
            (k_start + block_k - 1 + block_q - 1) // block_q)
    else:
        first = 0
        split = 0

    def body(qb, carry, *, masked):
        dk_acc, dv_acc = carry
        q_start = qb * block_q
        q_blk = q_ref[pl.dslice(q_start, block_q), :]
        if rope:
            q_blk = _rope_apply(q_blk, q_start, cos_ref, sinm_ref)
        do_blk = do_ref[pl.dslice(q_start, block_q), :]
        lse_blk = lse_ref[0, pl.dslice(q_start, block_q)].astype(jnp.float32)
        corr_blk = (
            dlse_ref[0, pl.dslice(q_start, block_q)].astype(jnp.float32)
            - delta_ref[0, pl.dslice(q_start, block_q)].astype(jnp.float32))
        scores = _dot(q_blk, k_t, trans_b=True) * sm_scale  # [bq, bk] fp32
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        p = jnp.exp(scores - lse_blk[:, None])
        p_cast = p.astype(do_blk.dtype)
        dv_acc = dv_acc + _dot(p_cast, do_blk, trans_a=True)  # p^T dO
        dp = _dot(do_blk, v_t, trans_b=True)
        ds = p * (dp + corr_blk[:, None])
        dk_acc = dk_acc + _dot(ds.astype(q_blk.dtype), q_blk, trans_a=True)
        return dk_acc, dv_acc

    carry = jax.lax.fori_loop(
        first, split, functools.partial(body, masked=causal),
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_acc, dv_acc = jax.lax.fori_loop(
        split, num_q_blocks, functools.partial(body, masked=False), carry)
    dk_acc = dk_acc * sm_scale
    if rope:
        dk_acc = _rope_apply(dk_acc, k_start, cos_ref, sinm_ref,
                             inverse=True)
    dk_ref[...] = dk_acc.astype(dk_ref.dtype)
    dv_ref[...] = dv_acc.astype(dv_ref.dtype)


def _rope_operands(s, d, rope, dtype):
    """(extra_inputs, extra_specs) for the rope tables — the [S, D]
    tables ride constant index maps, so Mosaic keeps them VMEM-resident
    across the grid like K/V. For bf16 inputs the tables are stored bf16
    too (the rotation multiplies promote to fp32 in-kernel): fp32 tables
    are 2x the VMEM — the difference between S=8192 fitting in the 16MB
    scoped-vmem budget and an OOM — and bf16 cos/sin error is below the
    bf16 matmul noise floor the scores already carry."""
    if not rope:
        return (), ()
    cos_t, sinm_t = _rope_tables(s, d)
    if dtype == jnp.bfloat16:
        cos_t, sinm_t = cos_t.astype(dtype), sinm_t.astype(dtype)
    spec = pl.BlockSpec((s, d), lambda b, i: (0, 0))
    return (cos_t, sinm_t), (spec, spec)


# ---------------------------------------------------------------------------
# Streaming (XL) kernels: K/V as a grid dimension
# ---------------------------------------------------------------------------
#
# The resident kernels above hold the full-sequence K/V (+ rope tables)
# in VMEM per grid row — the fastest layout while it fits, but a hard
# ceiling near S=8192 (bf16 K+V 4MB + tables 4MB + blocks against the
# 16MB scoped budget). The streaming variants below make the stationary
# side a grid dimension instead: Mosaic pipelines each K/V (or Q/dO)
# tile HBM->VMEM, online-softmax state lives in VMEM scratch across the
# revisited output block, and the result is written on the final visit.
# Cost vs resident at the same S: causal wastes the DMA of
# above-diagonal tiles (they are skipped compute-side) and the mask
# select runs on every tile — so the resident path stays the default
# and streaming engages only when residency would OOM
# (_needs_streaming), or explicitly for tests.

# Conservative budget for the resident path's stationary VMEM
# (K+V + rope tables), leaving headroom for blocks + double buffering
# inside the 16MB scoped window.
_RESIDENT_VMEM_BUDGET = 10 * 1024 * 1024
# (block_q, block_k) for the streaming kernels. Swept on v5e at S=16384
# (B1 H16 D128, rope, attention grad): (512,512) 64.3ms, (256,1024) 60.0,
# (1024,512) 55.4, (512,1024) 47.9, (2048,512) 44.8, (2048,1024) 42.4,
# (1024,1024) 42.8ms; (*,2048) OOMs scratch+blocks. Big square tiles win:
# fewer revisit flushes and better MXU occupancy amortize the per-tile
# mask/DMA tax.
STREAM_BLOCKS = (1024, 1024)


def _needs_streaming(s: int, d: int, dtype, rope: bool) -> bool:
    itemsize = jnp.dtype(dtype).itemsize
    resident = 2 * s * d * itemsize          # K + V (fwd/dq) or Q + dO
    if rope:
        resident += 2 * s * d * itemsize     # cos + sinm tables
    return resident > _RESIDENT_VMEM_BUDGET


def _stream_rope(x, cos_ref, sinm_ref, *, inverse: bool = False):
    """_rope_apply against tile-sliced table refs: the BlockSpec index
    map already positioned the (rows, d) slice at the tile's global
    rows, so the in-tile start is 0. One rotation implementation for
    both kernel families."""
    return _rope_apply(x, 0, cos_ref, sinm_ref, inverse=inverse)


def _stream_mask(scores, q_start, k_start, block_q, block_k):
    """Causal mask for one streamed tile. Applied unconditionally on the
    causal path (the tile-interior no-mask optimization of the resident
    kernels needs static loop bounds the grid does not give us); for
    fully-below-diagonal tiles the select is the identity."""
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, scores, NEG_INF)


def _fwd_stream_kernel(q_ref, k_ref, v_ref, *rest, block_q: int,
                       block_k: int, num_k_blocks: int, causal: bool,
                       sm_scale: float, rope: bool):
    """Grid (BH, q_blocks, k_blocks), k fastest. Scratch carries the
    online-softmax state across the k dimension; o/lse are written on the
    last k step (their index maps are constant in k, so Mosaic keeps the
    blocks resident until then)."""
    if rope:
        (cos_q, sinm_q, cos_k, sinm_k,
         o_ref, lse_ref, acc_ref, m_ref, den_ref) = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, den_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        den_ref[...] = jnp.zeros_like(den_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[...]
        k_blk = k_ref[...]
        if rope:
            q = _stream_rope(q, cos_q, sinm_q)
            k_blk = _stream_rope(k_blk, cos_k, sinm_k)
        scores = _dot(q, k_blk, trans_b=True) * sm_scale
        if causal:
            scores = _stream_mask(scores, q_start, k_start,
                                  block_q, block_k)
        blk_max = jnp.max(scores, axis=1)
        prev_max = m_ref[0, :]
        new_max = jnp.maximum(prev_max, blk_max)
        correction = jnp.exp(prev_max - new_max)
        p = jnp.exp(scores - new_max[:, None])
        acc_ref[...] = (acc_ref[...] * correction[:, None]
                        + _dot(p.astype(v_ref.dtype), v_ref[...]))
        den_ref[0, :] = den_ref[0, :] * correction + jnp.sum(p, axis=1)
        m_ref[0, :] = new_max

    if causal:
        # Tiles strictly above the diagonal contribute nothing (their
        # DMA still happens — the streaming tax).
        @pl.when(k_start <= q_start + block_q - 1)
        def _run():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _flush():
        den = den_ref[0, :]
        o_ref[...] = (acc_ref[...] / den[:, None]).astype(o_ref.dtype)
        lse_ref[0, :] = m_ref[0, :] + jnp.log(den)


def _bwd_dq_stream_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dlse_ref, *rest, block_q: int, block_k: int,
                          num_k_blocks: int, causal: bool,
                          sm_scale: float, rope: bool):
    """dQ with K/V streamed by the grid (BH, q_blocks, k_blocks)."""
    if rope:
        cos_q, sinm_q, cos_k, sinm_k, dq_ref, acc_ref = rest
    else:
        dq_ref, acc_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[...]
        k_blk = k_ref[...]
        if rope:
            q = _stream_rope(q, cos_q, sinm_q)
            k_blk = _stream_rope(k_blk, cos_k, sinm_k)
        lse = lse_ref[0, :].astype(jnp.float32)
        corr = (dlse_ref[0, :].astype(jnp.float32)
                - delta_ref[0, :].astype(jnp.float32))
        scores = _dot(q, k_blk, trans_b=True) * sm_scale
        if causal:
            scores = _stream_mask(scores, q_start, k_start,
                                  block_q, block_k)
        p = jnp.exp(scores - lse[:, None])
        dp = _dot(do_ref[...], v_ref[...], trans_b=True)
        ds = p * (dp + corr[:, None])
        acc_ref[...] = acc_ref[...] + _dot(ds.astype(k_blk.dtype), k_blk)

    if causal:
        @pl.when(k_start <= q_start + block_q - 1)
        def _run():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _flush():
        acc = acc_ref[...] * sm_scale
        if rope:
            acc = _stream_rope(acc, cos_q, sinm_q, inverse=True)
        dq_ref[...] = acc.astype(dq_ref.dtype)


def _bwd_dkv_stream_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dlse_ref, *rest, block_q: int,
                           block_k: int, num_q_blocks: int, causal: bool,
                           sm_scale: float, rope: bool):
    """dK/dV with Q/dO streamed by the grid (BH, k_blocks, q_blocks)."""
    if rope:
        (cos_q, sinm_q, cos_k, sinm_k,
         dk_ref, dv_ref, dk_acc, dv_acc) = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        k_t = k_ref[...]
        q_blk = q_ref[...]
        if rope:
            k_t = _stream_rope(k_t, cos_k, sinm_k)
            q_blk = _stream_rope(q_blk, cos_q, sinm_q)
        do_blk = do_ref[...]
        lse_blk = lse_ref[0, :].astype(jnp.float32)
        corr_blk = (dlse_ref[0, :].astype(jnp.float32)
                    - delta_ref[0, :].astype(jnp.float32))
        scores = _dot(q_blk, k_t, trans_b=True) * sm_scale
        if causal:
            scores = _stream_mask(scores, q_start, k_start,
                                  block_q, block_k)
        p = jnp.exp(scores - lse_blk[:, None])
        dv_acc[...] = dv_acc[...] + _dot(p.astype(do_blk.dtype), do_blk,
                                         trans_a=True)
        dp = _dot(do_blk, v_ref[...], trans_b=True)
        ds = p * (dp + corr_blk[:, None])
        dk_acc[...] = dk_acc[...] + _dot(ds.astype(q_blk.dtype), q_blk,
                                         trans_a=True)

    if causal:
        # Q tiles strictly left of this K tile's diagonal see none of it.
        @pl.when(q_start + block_q - 1 >= k_start)
        def _run():
            compute()
    else:
        compute()

    @pl.when(qi == num_q_blocks - 1)
    def _flush():
        dk = dk_acc[...] * sm_scale
        if rope:
            dk = _stream_rope(dk, cos_k, sinm_k, inverse=True)
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _stream_rope_operands(s, d, rope, dtype, block_q, block_k, qk_order,
                          causal=False):
    """Rope table operands for the streaming kernels: the SAME [S, D]
    tables passed twice, sliced per-tile by the grid — a (block_q, d)
    view following the q axis and a (block_k, d) view following the k
    axis. qk_order: 'qk' for grid (b, qi, ki) (fwd/dq), 'kq' for
    (b, ki, qi) (dkv). The streamed axis's view is clamped like its
    K/V (or Q/dO) companion so skipped tiles elide their table DMA too
    (_clamp_ki/_clamp_qi)."""
    if not rope:
        return (), ()
    cos_t, sinm_t = _rope_tables(s, d)
    if dtype == jnp.bfloat16:
        cos_t, sinm_t = cos_t.astype(dtype), sinm_t.astype(dtype)
    if qk_order == "qk":
        kidx = _clamp_ki(causal, block_q, block_k)

        def k_tbl(b, qi, ki):
            # Same clamp as the K/V stream (single source of truth —
            # _clamp_ki); the table view just drops the batch element.
            _, kk, _ = kidx(b, qi, ki)
            return (kk, 0)
        q_spec = pl.BlockSpec((block_q, d), lambda b, qi, ki: (qi, 0))
        k_spec = pl.BlockSpec((block_k, d), k_tbl)
    else:
        qidx = _clamp_qi(causal, block_q, block_k)

        def q_tbl(b, ki, qi):
            _, qq, _ = qidx(b, ki, qi)
            return (qq, 0)
        q_spec = pl.BlockSpec((block_q, d), q_tbl)
        k_spec = pl.BlockSpec((block_k, d), lambda b, ki, qi: (ki, 0))
    return ((cos_t, sinm_t, cos_t, sinm_t),
            (q_spec, q_spec, k_spec, k_spec))


def _clamp_ki(causal, block_q, block_k):
    """K-tile index for grid (b, qi, ki). Causal: tiles strictly above
    the diagonal are compute-skipped in the kernel; CLAMPING their index
    to the last needed tile makes consecutive skipped iterations resolve
    to the same block, so Mosaic elides their DMA entirely (the
    streaming tax drops from 2x K-stream traffic to ~1x)."""
    if not causal:
        return lambda b, qi, ki: (b, ki, 0)

    def idx(b, qi, ki):
        last = (qi * block_q + block_q - 1) // block_k
        return (b, jnp.minimum(ki, last), 0)
    return idx


def _clamp_qi(causal, block_q, block_k):
    """Q-tile index for grid (b, ki, qi) (dkv): tiles strictly left of
    this K tile's diagonal are skipped; clamp them UP to the first
    needed tile for the same DMA elision."""
    if not causal:
        return lambda b, ki, qi: (b, qi, 0)

    def idx(b, ki, qi):
        first = (ki * block_k) // block_q
        return (b, jnp.maximum(qi, first), 0)
    return idx


def _fwd_call_stream(q, k, v, causal, block_q, block_k, interpret, rope):
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    num_k = s // block_k
    kernel = functools.partial(
        _fwd_stream_kernel, block_q=block_q, block_k=block_k,
        num_k_blocks=num_k, causal=causal, sm_scale=sm_scale, rope=rope)
    rope_in, rope_specs = _stream_rope_operands(s, d, rope, q.dtype,
                                                block_q, block_k, "qk",
                                                causal=causal)
    k_idx = _clamp_ki(causal, block_q, block_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, s // block_q, num_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_k, d), k_idx),
            pl.BlockSpec((None, block_k, d), k_idx),
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((1, block_q), jnp.float32),   # running max
            pltpu.VMEM((1, block_q), jnp.float32),   # denom
        ],
        interpret=interpret,
    )(q, k, v, *rope_in)


def _bwd_calls_stream(q, k, v, dout, lse, delta, dlse, causal, block_q,
                      block_k, interpret, rope):
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    num_q = s // block_q
    num_k = s // block_k

    dq_kernel = functools.partial(
        _bwd_dq_stream_kernel, block_q=block_q, block_k=block_k,
        num_k_blocks=num_k, causal=causal, sm_scale=sm_scale, rope=rope)
    rope_in, rope_specs = _stream_rope_operands(s, d, rope, q.dtype,
                                                block_q, block_k, "qk",
                                                causal=causal)
    row_spec = pl.BlockSpec((None, 1, block_q), lambda b, qi, ki: (b, 0, qi))
    k_idx = _clamp_ki(causal, block_q, block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_k, d), k_idx),
            pl.BlockSpec((None, block_k, d), k_idx),
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            row_spec, row_spec, row_spec,
            *rope_specs,
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta, dlse, *rope_in)

    dkv_kernel = functools.partial(
        _bwd_dkv_stream_kernel, block_q=block_q, block_k=block_k,
        num_q_blocks=num_q, causal=causal, sm_scale=sm_scale, rope=rope)
    rope_in, rope_specs = _stream_rope_operands(s, d, rope, q.dtype,
                                                block_q, block_k, "kq",
                                                causal=causal)
    q_idx = _clamp_qi(causal, block_q, block_k)

    def q_row_idx(b, ki, qi):
        b_, clamped, _ = q_idx(b, ki, qi)
        return (b_, 0, clamped)

    row_spec_kq = pl.BlockSpec((None, 1, block_q), q_row_idx)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), q_idx),
            pl.BlockSpec((None, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_q, d), q_idx),
            row_spec_kq, row_spec_kq, row_spec_kq,
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),   # dk acc
            pltpu.VMEM((block_k, d), jnp.float32),   # dv acc
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta, dlse, *rope_in)
    return dq, dk, dv


def _fwd_call(q, k, v, causal, block_q, block_k, interpret, rope):
    """q, k, v: [BH, S, D] -> (out [BH, S, D], lse [BH, S] fp32)."""
    bh, s, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, seq_len=s,
                               causal=causal, sm_scale=sm_scale, rope=rope)
    rope_in, rope_specs = _rope_operands(s, d, rope, q.dtype)
    return pl.pallas_call(
        kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, qi: (b, 0, 0)),
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, qi: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, *rope_in)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9,
                                                    10))
def _flash(q, k, v, causal, block_q, block_k, bwd_block_q, bwd_block_k,
           interpret, rope, streaming):
    """[BH, S, D] primitive returning (out, lse [BH, 1, S] fp32).

    Both outputs are differentiable: an out-only consumer gets a zero
    dlse cotangent from JAX and the backward degenerates to plain flash;
    ring attention consumes BOTH (partials are merged by lse weights).
    bwd_block_{q,k} tile the two backward kernels independently of the
    forward (long sequences want a wider bwd K window; the forward OOMs
    VMEM there). streaming=True selects the XL kernels (K/V as a grid
    dimension) — the path for sequences whose K/V + rope tables exceed
    the resident kernels' VMEM budget."""
    fwd = _fwd_call_stream if streaming else _fwd_call
    return fwd(q, k, v, causal, block_q, block_k, interpret, rope)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, bwd_block_q,
                    bwd_block_k, interpret, rope, streaming):
    fwd = _fwd_call_stream if streaming else _fwd_call
    out, lse = fwd(q, k, v, causal, block_q, block_k, interpret, rope)
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd_rule(causal, fwd_block_q, fwd_block_k, block_q, block_k,
                    interpret, rope, streaming, res, cts):
    q, k, v, out, lse = res
    dout, dlse = cts
    dout = dout.astype(q.dtype)
    dlse = dlse.astype(jnp.float32)
    bh, s, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    # delta_i = dO_i . O_i: one fused elementwise+reduce in HBM; tiny next
    # to the matmuls and XLA fuses it with the incoming cotangent.
    # [BH, 1, S] like lse (Mosaic block-shape constraint, see _fwd_kernel).
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]
    if streaming:
        return _bwd_calls_stream(q, k, v, dout, lse, delta, dlse, causal,
                                 block_q, block_k, interpret, rope)

    rope_in, rope_specs = _rope_operands(s, d, rope, q.dtype)
    dq_kernel = functools.partial(_bwd_dq_kernel, block_k=block_k,
                                  seq_len=s, causal=causal,
                                  sm_scale=sm_scale, rope=rope)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, qi: (b, 0, qi)),
            pl.BlockSpec((None, 1, block_q), lambda b, qi: (b, 0, qi)),
            pl.BlockSpec((None, 1, block_q), lambda b, qi: (b, 0, qi)),
            *rope_specs,
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, dout, lse, delta, dlse, *rope_in)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, block_q=block_q,
                                   seq_len=s, causal=causal,
                                   sm_scale=sm_scale, rope=rope)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((None, s, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((None, s, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b, ki: (b, 0, 0)),
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta, dlse, *rope_in)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             block_q: int = 0,
                             block_k: int = 0,
                             bwd_block_q: int = 0,
                             bwd_block_k: int = 0,
                             interpret: bool = False,
                             rope: bool = False,
                             streaming=None):
    """q, k, v: [B, S, H, D] -> (out [B, S, H, D], lse [B, H, S] fp32).

    Differentiable in BOTH outputs (joint custom VJP): lse is the per-row
    logsumexp of the scaled scores, which makes per-call results
    mergeable — ring attention combines ring-step partials as
    o = sum_i o_i * exp(lse_i - logsumexp_i(lse_i)). Causal inputs are
    zero-padded up to the block size — exact, since padded keys are above
    every real row's diagonal and padded rows are sliced off; non-causal
    S must divide by the blocks (padded keys would shift its softmax).

    rope=True applies rope_half to q/k INSIDE the kernels with positions
    = sequence index (padded rows get out-of-range positions, harmless:
    padded keys are causally masked and padded rows are sliced off).
    Ring attention must keep rope outside (its visiting K blocks carry
    other shards' global positions, which the kernel cannot know).

    block_q/block_k tile the forward, bwd_block_q/bwd_block_k the two
    backward kernels; 0 (default) = the swept optimum for this sequence
    length (default_blocks / default_bwd_blocks). When forward blocks
    are given explicitly but backward ones are not, the backward
    inherits the forward's (callers with odd local lengths — ring
    attention — chose dividing blocks on purpose)."""
    b, s, h, d = q.shape
    explicit_fwd = bool(block_q or block_k)
    # Lane-aligned length (causal pads up to it; non-causal cannot pad):
    # block defaults are chosen against it so they never ADD padding
    # beyond the forward's, nor break the non-causal divisibility rule.
    s_eff = s + (-s) % LANES if causal else s
    # streaming=None (default): engage the XL kernels exactly when the
    # resident kernels' stationary K/V + rope tables would exceed the
    # VMEM budget (e.g. S >= ~16384 at D=128 bf16 with rope).
    if streaming is None:
        streaming = _needs_streaming(s_eff, d, q.dtype, rope)
    if not block_q or not block_k:
        if streaming:
            sq, sk = STREAM_BLOCKS
            dq_, dk_ = (sq, sk) if (s_eff % sq == 0 and s_eff % sk == 0) \
                else (DEFAULT_BLOCK, DEFAULT_BLOCK)
        else:
            dq_, dk_ = default_blocks(s)
        block_q = block_q or dq_
        block_k = block_k or dk_
    if not bwd_block_q or not bwd_block_k:
        if explicit_fwd or streaming:
            # Streaming bwd kernels share the fwd's streamed tiling.
            dq_, dk_ = (block_q, block_k)
        else:
            dq_, dk_ = default_bwd_blocks(s_eff)
        bwd_block_q = bwd_block_q or dq_
        bwd_block_k = bwd_block_k or dk_
    if causal:
        # Lane-align first (Mosaic tiling wants 8/128-aligned or full-size
        # block dims), then block-align so the grid divides evenly.
        block_q = min(block_q, s_eff)
        block_k = min(block_k, s_eff)
        bwd_block_q = min(bwd_block_q, s_eff)
        bwd_block_k = min(bwd_block_k, s_eff)
        lcm = 1
        for blk in (block_q, block_k, bwd_block_q, bwd_block_k):
            lcm = lcm * blk // math.gcd(lcm, blk)
        pad = (s_eff + (-s_eff) % lcm) - s
    else:
        block_q = min(block_q, s)
        block_k = min(block_k, s)
        bwd_block_q = min(bwd_block_q, s)
        bwd_block_k = min(bwd_block_k, s)
        for blk in (block_q, block_k, bwd_block_q, bwd_block_k):
            if s % blk:
                raise ValueError(f"seq len {s} not divisible by blocks "
                                 f"({block_q}, {block_k}, {bwd_block_q}, "
                                 f"{bwd_block_k})")
        pad = 0
    if pad:
        zeros = ((0, 0), (0, pad), (0, 0), (0, 0))
        q, k, v = (jnp.pad(x, zeros) for x in (q, k, v))
        s += pad

    # [B,S,H,D] -> [B*H, S, D]: one grid row per (batch, head).
    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)

    out, lse = _flash(to_bh(q), to_bh(k), to_bh(v), causal, block_q,
                      block_k, bwd_block_q, bwd_block_k, interpret, rope,
                      streaming)
    out = jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))
    lse = lse.reshape(b, h, s)
    if pad:
        out, lse = out[:, :s - pad], lse[..., :s - pad]
    return out, lse


def flash_attention(q, k, v, *, causal: bool = True,
                    block_q: int = 0,
                    block_k: int = 0,
                    bwd_block_q: int = 0,
                    bwd_block_k: int = 0, interpret: bool = False,
                    rope: bool = False, streaming=None):
    """q, k, v: [B, S, H, D] -> [B, S, H, D]. Differentiable (custom VJP
    with tiled backward kernels); see flash_attention_with_lse for the
    padding/divisibility, fused-rope, and streaming contracts."""
    out, _ = flash_attention_with_lse(q, k, v, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      bwd_block_q=bwd_block_q,
                                      bwd_block_k=bwd_block_k,
                                      interpret=interpret, rope=rope,
                                      streaming=streaming)
    return out


def attend(q, k, v, *, causal: bool = True, impl: str = "auto",
           platform: str = "", rope: bool = False):
    """Attention entrypoint for the workload models.

    impl: "auto" (pallas kernel on TPU, jnp reference elsewhere),
    "flash" (force the kernel), "flash_interpret" (kernel in interpret
    mode — CPU-testable numerics), "reference" (plain jnp).

    rope=True fuses rope_half (positions = sequence index) into whichever
    path is chosen — in-kernel on the flash path, external on the jnp
    path — so all impls compute the same function.

    platform: the caller's statement of what the computation runs on
    ("tpu"/"cpu") — callers that hold a Mesh must pass it (model.py
    make_train_step does). A traced body cannot see its own devices, and
    the jax.devices() fallback reflects the DEFAULT backend, which is
    wrong for e.g. a CPU mesh on a TPU-equipped host.
    """
    from tpu_dra.workloads.ringattention import reference_attention

    def fallback(q, k, v, causal):
        # Non-kernel path computes the SAME function: rope applied
        # externally with the matching (half-split) pairing.
        if rope:
            positions = jnp.arange(q.shape[1])[None, :]
            q, k = rope_half(q, positions), rope_half(k, positions)
        return reference_attention(q, k, v, causal=causal)

    if impl == "reference":
        return fallback(q, k, v, causal)
    if impl == "auto":
        if not platform:
            platform = default_platform()
        if not (platform == "tpu" and q.shape[1] >= LANES):
            return fallback(q, k, v, causal)
        if not causal:
            # Non-causal can't be zero-padded (padded keys would shift the
            # softmax): kernel only when a block size divides S evenly.
            for blk in (DEFAULT_BLOCK, LANES):
                if q.shape[1] % blk == 0:
                    return flash_attention(q, k, v, causal=False,
                                           block_q=blk, block_k=blk,
                                           rope=rope)
            return fallback(q, k, v, causal=False)
        return flash_attention(q, k, v, causal=True, rope=rope)
    if impl in ("flash", "flash_interpret"):
        return flash_attention(q, k, v, causal=causal,
                               interpret=impl == "flash_interpret",
                               rope=rope)
    raise ValueError(f"unknown attention impl {impl!r}")
