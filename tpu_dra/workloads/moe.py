"""Expert-parallel MoE feed-forward (the `ep` axis of SURVEY §2.10).

Top-1 token-choice routing with experts sharded over an `expert` mesh
axis. The design is TPU-first, not a port:

- Dense one-hot dispatch/combine einsums rather than scatter/gather —
  static shapes, MXU-friendly, XLA fuses the mask into the matmuls
  (pallas_guide.md: avoid dynamic shapes inside jit).
- shard_map over the expert axis: each device holds its local experts'
  weights and the FULL token batch (replicated), computes its local
  expert outputs, and a single psum combines — the all-to-all dispatch
  degenerates to one reduction because dispatch masks zero out foreign
  tokens. For the capacity-bound variant the mask also enforces per-expert
  token capacity, dropping overflow (standard Switch-style routing).
- No data-dependent Python control flow: routing is argmax + one-hot,
  capacity is cumsum + mask (lax-friendly, compiles once).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import shard_map


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.bfloat16) -> Dict:
    kr, ku, kd = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_out = 1.0 / np.sqrt(d_ff)
    return {
        "router": (jax.random.normal(kr, (d_model, n_experts), jnp.float32)
                   * scale_in),
        "w_up": (jax.random.normal(ku, (n_experts, d_model, d_ff), jnp.float32)
                 * scale_in).astype(dtype),
        "w_down": (jax.random.normal(kd, (n_experts, d_ff, d_model),
                                     jnp.float32) * scale_out).astype(dtype),
    }


def route_top1(x, router_w, n_experts: int, capacity: int):
    """Returns (dispatch [B,S,E,C], combine [B,S,E,C], aux_loss).

    Dense dispatch/combine tensors (Switch Transformer style): position c
    of expert e holds token (b,s) iff that token routed to e within
    capacity. Router math in fp32 (small, precision-sensitive).
    """
    logits = x.astype(jnp.float32) @ router_w  # [B,S,E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [B,S]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
    # Position within the expert's capacity, in (b,s) order.
    pos = jnp.cumsum(onehot.reshape(-1, n_experts), axis=0) * \
        onehot.reshape(-1, n_experts) - 1.0
    pos = pos.reshape(onehot.shape)                          # [B,S,E]
    keep = (pos >= 0) & (pos < capacity)
    pos_cap = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = (jax.nn.one_hot(pos_cap, capacity, dtype=jnp.float32)
                * (onehot * keep)[..., None])                # [B,S,E,C]
    gate = jnp.max(probs * onehot, axis=-1)                  # [B,S]
    combine = dispatch * gate[..., None, None]
    # Load-balancing aux loss (mean prob * mean assignment per expert).
    density = onehot.mean(axis=(0, 1))
    density_proxy = probs.mean(axis=(0, 1))
    aux = (density * density_proxy).sum() * (n_experts ** 2)
    return dispatch, combine, aux


def moe_ffn(params: Dict, x, *, capacity_factor: float = 1.25,
            compute_dtype=jnp.float32):
    """Reference (unsharded) MoE FFN: x [B,S,D] -> [B,S,D].

    Routing math stays fp32 (route_top1); the expert matmuls run in
    `compute_dtype` — bf16 from the MoE transformer (the MXU fast path,
    like the dense FFN's `h @ w.astype(cfg.dtype)`), fp32 by default for
    the standalone/EP-parity tests. Dispatch/combine are exact 0/1-and-
    gate tensors, safe to cast."""
    n_experts = params["router"].shape[-1]
    B, S, D = x.shape
    capacity = max(1, int(capacity_factor * B * S / n_experts))
    dispatch, combine, aux = route_top1(x, params["router"], n_experts,
                                        capacity)
    cd = compute_dtype
    # Dispatch tokens to expert buffers: [E, C, D].
    buffers = jnp.einsum("bsec,bsd->ecd", dispatch.astype(cd), x.astype(cd))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", buffers,
                               params["w_up"].astype(cd)))
    out_buf = jnp.einsum("ecf,efd->ecd", h,
                         params["w_down"].astype(cd))
    out = jnp.einsum("bsec,ecd->bsd", combine.astype(cd), out_buf)
    return out.astype(x.dtype), aux


def make_expert_parallel_ffn(mesh: Mesh, axis_name: str = "expert",
                             capacity_factor: float = 1.25):
    """Jitted expert-parallel MoE FFN over `mesh`'s expert axis.

    Expert weights are sharded on their leading (expert) dim; activations
    are replicated. Each device computes its local experts' contribution;
    one psum combines — dispatch masks make foreign-expert terms zero.
    """
    def body(params, x):
        n_local = params["w_up"].shape[0]
        n_experts = n_local * jax.lax.psum(1, axis_name)
        my = jax.lax.axis_index(axis_name)
        B, S, _ = x.shape
        capacity = max(1, int(capacity_factor * B * S / n_experts))
        dispatch, combine, aux = route_top1(x, params["router"], n_experts,
                                            capacity)
        # Slice MY experts out of the dense dispatch/combine tensors.
        sl = jax.lax.dynamic_slice_in_dim(dispatch, my * n_local, n_local, 2)
        cb = jax.lax.dynamic_slice_in_dim(combine, my * n_local, n_local, 2)
        buffers = jnp.einsum("bsec,bsd->ecd", sl, x.astype(jnp.float32))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", buffers,
                                   params["w_up"].astype(jnp.float32)))
        out_buf = jnp.einsum("ecf,efd->ecd", h,
                             params["w_down"].astype(jnp.float32))
        out = jnp.einsum("bsec,ecd->bsd", cb, out_buf)
        return jax.lax.psum(out, axis_name).astype(x.dtype), aux

    param_specs = {"router": P(), "w_up": P(axis_name, None, None),
                   "w_down": P(axis_name, None, None)}
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(fn)


def shard_moe_params(params: Dict, mesh: Mesh,
                     axis_name: str = "expert") -> Dict:
    specs = {"router": P(), "w_up": P(axis_name, None, None),
             "w_down": P(axis_name, None, None)}
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}
