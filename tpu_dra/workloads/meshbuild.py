"""Multi-process JAX mesh construction from a driver allocation.

The JAX half of the allocation → mesh contract (SURVEY §17; control-
plane half: ``tpu_dra.topology.meshexport``). A prepared claim's CDI
env names the chips, their torus coordinates, and the worker's identity
(``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` from the cddaemon); the
:class:`~tpu_dra.topology.meshexport.MeshPlan` built from that env
fixes a deterministic rank→coordinate order. This module lays actual
``jax.sharding.Mesh`` axes over JAX devices in THAT order, so every
workload in ``tpu_dra/workloads`` runs on topology-allocated devices —
ring steps ride ICI neighbor links — rather than ambient
``jax.devices()`` in whatever order the runtime enumerated them.

``launch_workload`` is the mesh-parameterized entry point over the
workload library (allreduce, ringattention, ulysses, moe, pipeline,
sp_train): small, measured runs returning per-workload bandwidth or
throughput, used by the bench's data-plane phase and injectable into
tests. Every launch passes the ``workload.launch`` admission seam and
every mesh build the ``mesh.build`` one, so both failure modes are
chaos-drivable.

JAX is imported lazily inside functions: the control plane imports this
module's siblings without paying for (or requiring) a JAX runtime.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

from tpu_dra.infra.metrics import PSUM_BW
from tpu_dra.topology.meshexport import (  # noqa: F401  (re-exported API)
    MeshBuildError, MeshPlan, admit_launch, plan_from_env,
    plan_from_worker_envs,
)


def ordered_devices(plan: MeshPlan, devices: Sequence) -> List:
    """Permute `devices` into the plan's rank order. `devices` is the
    arrival-order device list — one JAX device per allocated chip,
    aligned with the plan's (worker_index, chip_index)-sorted arrival
    order (worker-major, chip ascending: the order a multi-process
    runtime enumerates a slice). Refuses a count mismatch: a mesh over
    the wrong device count is a rank/topology lie."""
    if len(devices) != plan.n_devices:
        raise MeshBuildError(
            f"allocation plans {plan.n_devices} devices but "
            f"{len(devices)} JAX devices were supplied")
    return [devices[i] for i in plan.order]


def mesh_from_plan(plan: MeshPlan, devices: Sequence,
                   axis_names: Sequence[str] = ("x",),
                   shape: Optional[Sequence[int]] = None):
    """A ``jax.sharding.Mesh`` whose device order follows the allocated
    torus coordinates. Default is the 1-D collective mesh; pass
    `axis_names` + `shape` for N-D layouts (the product must equal the
    device count — checked, not truncated)."""
    import numpy as np
    from jax.sharding import Mesh

    devs = ordered_devices(plan, devices)
    if shape is None:
        shape = (len(devs),) if len(axis_names) == 1 else None
    if shape is None or len(shape) != len(axis_names):
        raise MeshBuildError(
            f"axis_names {tuple(axis_names)} need an explicit shape")
    n = 1
    for d in shape:
        n *= d
    if n != len(devs):
        raise MeshBuildError(
            f"mesh shape {tuple(shape)} holds {n} devices but the "
            f"allocation has {len(devs)}")
    return Mesh(np.array(devs).reshape(tuple(shape)), tuple(axis_names))


def _sync_scalar(x) -> float:
    """Fetch one scalar from (possibly nested) output — the only
    synchronization barrier that holds on every PJRT backend."""
    import jax
    leaf = jax.tree.leaves(x)[0]
    return float(leaf.reshape(-1)[0])


def _timed(fn: Callable, *args, iters: int = 2) -> float:
    """Mean wall seconds per call after one compile+warm call. Every
    iteration is synchronized by a scalar fetch: the calls share their
    inputs, so a final-output-only fetch would let independent
    dispatches overlap on backends that run computations concurrently
    (PJRT CPU) and inflate the reported rate — the same pitfall
    allreduce_bandwidth documents and avoids by data-chaining."""
    _sync_scalar(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        _sync_scalar(fn(*args))
    return (time.perf_counter() - t0) / iters


# ---------------------------------------------------------------------------
# Per-workload launchers (small, measured; shapes scale with the mesh)
# ---------------------------------------------------------------------------

def _run_allreduce(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    from tpu_dra.workloads.allreduce import allreduce_bandwidth

    r = allreduce_bandwidth(
        nbytes_per_device=int(kw.get("nbytes_per_device", 1 << 18)),
        iters=int(kw.get("iters", 4)), warmup=2,
        devices=ordered_devices(plan, devices))
    if r["algo_gbps"] > 0:
        PSUM_BW.observe(r["algo_gbps"])
    return {"algo_gbps": round(r["algo_gbps"], 3),
            "bus_gbps": round(r["bus_gbps"], 3),
            "n_devices": int(r["n_devices"])}


def _attention_inputs(n: int, heads: int, s_local: int = 8, b: int = 2,
                      d: int = 16):
    import numpy as np
    import jax.numpy as jnp

    shape = (b, n * s_local, heads, d)
    return [jnp.asarray(np.random.RandomState(i).standard_normal(shape),
                        jnp.float32) for i in range(3)], shape


def _run_ringattention(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    from tpu_dra.workloads.ringattention import make_ring_attention

    mesh = mesh_from_plan(plan, devices, axis_names=("seq",))
    n = plan.n_devices
    qkv, shape = _attention_inputs(n, heads=2)
    fn = make_ring_attention(mesh, axis_name="seq")
    wall_s = _timed(lambda q, k, v: fn(q, k, v), *qkv,
                    iters=int(kw.get("iters", 2)))
    b, s, h, d = shape
    flops = 4.0 * b * s * s * h * d  # qk^T + att@v, forward
    return {"wall_ms": round(wall_s * 1e3, 3),
            "gflops_per_s": round(flops / wall_s / 1e9, 3),
            "seq": s}


def _run_ulysses(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    from tpu_dra.workloads.ulysses import make_ulysses_attention

    mesh = mesh_from_plan(plan, devices, axis_names=("seq",))
    n = plan.n_devices
    qkv, shape = _attention_inputs(n, heads=n)  # H % axis_size == 0
    fn = make_ulysses_attention(mesh, axis_name="seq")
    wall_s = _timed(lambda q, k, v: fn(q, k, v), *qkv,
                    iters=int(kw.get("iters", 2)))
    b, s, h, d = shape
    flops = 4.0 * b * s * s * h * d
    return {"wall_ms": round(wall_s * 1e3, 3),
            "gflops_per_s": round(flops / wall_s / 1e9, 3),
            "seq": s}


def _run_moe(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.moe import (
        init_moe_params, make_expert_parallel_ffn, shard_moe_params,
    )

    mesh = mesh_from_plan(plan, devices, axis_names=("expert",))
    n = plan.n_devices
    d_model, d_ff = 16, 32
    params = shard_moe_params(
        init_moe_params(jax.random.PRNGKey(1), d_model, d_ff, n,
                        dtype=jnp.float32), mesh)
    x = jnp.asarray(np.random.RandomState(3).standard_normal(
        (2, 16, d_model)), jnp.float32)
    fn = make_expert_parallel_ffn(mesh)
    wall_s = _timed(lambda p, v: fn(p, v)[0], params, x,
                    iters=int(kw.get("iters", 2)))
    b, s, _ = x.shape
    tokens = b * s
    flops = 2.0 * tokens * d_model * d_ff * 2  # up + down matmuls, fwd
    return {"wall_ms": round(wall_s * 1e3, 3),
            "gflops_per_s": round(flops / wall_s / 1e9, 3),
            "tokens_per_s": round(tokens / wall_s, 1)}


def _run_pipeline(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.pipeline import (
        init_stage_params, make_pipeline_forward, shard_stage_params,
    )

    mesh = mesh_from_plan(plan, devices, axis_names=("stage",))
    n = plan.n_devices
    d = 16
    weights = shard_stage_params(
        init_stage_params(jax.random.PRNGKey(2), n, d), mesh)
    mbs = jnp.asarray(np.random.RandomState(4).standard_normal((6, 2, d)),
                      jnp.float32)
    fn = make_pipeline_forward(mesh)
    wall_s = _timed(lambda w, m: fn(w, m), weights, mbs,
                    iters=int(kw.get("iters", 2)))
    return {"wall_ms": round(wall_s * 1e3, 3),
            "microbatches_per_s": round(mbs.shape[0] / wall_s, 1),
            "stages": n}


def _run_sp_train(plan: MeshPlan, devices: Sequence, **kw) -> Dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.model import (
        ModelConfig, TransformerLM, init_params,
    )
    from tpu_dra.workloads.sp_train import make_sp_train_step

    mesh = mesh_from_plan(plan, devices, axis_names=("seq",))
    n = plan.n_devices
    cfg = ModelConfig(vocab=64, d_model=n * 4, n_heads=n, n_layers=2,
                      d_ff=64, max_seq=n * 8, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(11), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(12).randint(0, cfg.vocab, (2, cfg.max_seq)),
        dtype=jnp.int32)
    step = make_sp_train_step(TransformerLM(cfg), mesh)
    wall_s = _timed(lambda p, t: step(p, t)[1], params, tokens,
                    iters=int(kw.get("iters", 2)))
    tokens_per_step = tokens.shape[0] * (cfg.max_seq - 1)
    return {"wall_ms": round(wall_s * 1e3, 3),
            "tokens_per_s": round(tokens_per_step / wall_s, 1),
            "seq": cfg.max_seq}


WORKLOADS: Dict[str, Callable] = {
    "allreduce": _run_allreduce,
    "ringattention": _run_ringattention,
    "ulysses": _run_ulysses,
    "moe": _run_moe,
    "pipeline": _run_pipeline,
    "sp_train": _run_sp_train,
}


def launch_workload(name: str, plan: MeshPlan, devices: Sequence,
                    **kw) -> Dict:
    """Run workload `name` on the allocation's mesh and return its
    metric record ({wall_ms, bandwidth or rate, ...}). Unknown names
    refuse (a typo must not read as 'workload passed'); the
    workload.launch admission seam runs first so launch failures are
    chaos-drivable."""
    if name not in WORKLOADS:
        raise MeshBuildError(
            f"unknown workload {name!r} (known: {sorted(WORKLOADS)})")
    admit_launch(name)
    return WORKLOADS[name](plan, devices, **kw)
