"""All-reduce (psum) bandwidth probe over a device mesh.

The workload-side analog of the reference's NCCL broadcast / nvbandwidth
assertions (tests/bats/test_cd_mnnvl_workload.bats:18-45): a JAX ``psum``
across every visible device, timed, reported as *algorithm bandwidth*
(payload bytes / time) and *bus bandwidth* (scaled by ``2*(n-1)/n``, the
standard ring all-reduce traffic factor, so numbers are comparable across
device counts and to NCCL-style reporting).

On a driver-provisioned slice the devices JAX sees are exactly the chips the
DRA claim allocated (``TPU_VISIBLE_CHIPS`` from the claim's CDI spec), so
this measures the ICI path the ComputeDomain stitched together.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import shard_map


def device_put_sharded_uniform(nbytes_per_device: int, devices: List
                               ) -> jax.Array:
    """One bf16 shard of `nbytes_per_device` on each device, stacked on a
    1-D 'x' mesh (leading dim = device count). Shards are created directly
    under the sharding — no full-array staging on device 0."""
    n = len(devices)
    elems = max(1, nbytes_per_device // 2)  # bfloat16 = 2 bytes
    sharding = NamedSharding(Mesh(devices, ("x",)), P("x"))
    make = jax.jit(lambda: jnp.ones((n, elems), dtype=jnp.bfloat16),
                   out_shardings=sharding)
    return make()


def local_hbm_bandwidth(nbytes: int = 64 << 20, iters: int = 1000,
                        warmup: int = 2, reps: int = 3,
                        device=None) -> Dict[str, float]:
    """Single-device HBM-bandwidth proxy: a long chain of elementwise
    scales over an `nbytes` bf16 buffer, reported as
    (read+write bytes)/time per iteration.

    This is the stand-in perf trend when only one chip is visible and the
    psum phase honestly reports 0 (no collective exists to measure): it
    exercises the same HBM path an on-chip collective's local phase rides,
    so regressions in the memory system still show up cross-round.

    Measurement design, each part load-bearing on remote-tunnel platforms:
    - the k-step chain lives INSIDE one jit (`lax.fori_loop`) — per-call
      dispatch costs milliseconds and would swamp the ~0.2ms of real HBM
      traffic per step;
    - the scale factor is data-dependent (u[i]), so no XLA pass can fold
      the iterations into one sweep (loop-invariant bodies measured as
      terabytes/s after fusion; conservative: each step also pays the
      scalar-gather serialization);
    - iters is LARGE (default 1000 ~ 200ms of compute) and the two-point
      delta takes min-of-reps: the scalar-fetch sync barrier has tens of
      ms of jitter on tunneled platforms, which buries any smaller signal
      (measured: 10-iter deltas came out negative).
    """
    if device is None:
        device = jax.devices()[0]
    elems = max(1, nbytes // 2)
    with jax.default_device(device):
        x = jnp.ones((elems,), jnp.bfloat16)

    eps = jnp.asarray(1e-8, jnp.bfloat16)
    one = jnp.asarray(1.0, jnp.bfloat16)

    @partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
    def steps(v, k):
        return jax.lax.fori_loop(
            0, k,
            lambda i, u: u * (one + eps * u[i].astype(jnp.bfloat16)), v)

    state = {"v": x}

    def run(k: int) -> float:
        # Scalar fetch as the sync barrier (block_until_ready is a no-op
        # on remote-tunnel platforms); its RTT cancels in the two-point
        # min-delta below.
        t0 = time.perf_counter()
        v = steps(state["v"], k)
        float(v[0])
        state["v"] = v
        return time.perf_counter() - t0

    for _ in range(max(1, warmup)):
        run(1)
        run(1 + iters)  # both step counts have distinct compilations
    t_small = min(run(1) for _ in range(reps))
    t_big = min(run(1 + iters) for _ in range(reps))
    mean_s = max((t_big - t_small) / iters, 1e-9)
    nbytes_moved = 2 * x.dtype.itemsize * elems  # one read + one write
    return {"hbm_proxy_gbps": nbytes_moved / mean_s / 1e9,
            "payload_mib": (x.dtype.itemsize * elems) / (1 << 20),
            "mean_s": mean_s}


def allreduce_bandwidth(nbytes_per_device: int = 64 << 20,
                        iters: int = 10, warmup: int = 3,
                        devices: Optional[List] = None) -> Dict[str, float]:
    """Time `psum` over all (or the given) devices.

    Returns {algo_gbps, bus_gbps, n_devices, payload_mib, mean_s}.
    Single-device degenerates to an identity (no collective at all);
    both rates are reported as 0 in that case to avoid misleading numbers.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n == 1:
        # The psum is an identity XLA compiles away entirely: there is
        # nothing to measure, so skip the compile+timing and return an
        # all-zero record rather than payload/epsilon nonsense.
        return {"algo_gbps": 0.0, "bus_gbps": 0.0, "n_devices": 1.0,
                "payload_mib": nbytes_per_device / (1 << 20), "mean_s": 0.0}
    x = device_put_sharded_uniform(nbytes_per_device, devices)
    # Single source of truth for the mesh: the one the input is sharded on.
    mesh = x.sharding.mesh

    inv_n = 1.0 / n
    # Payload metadata is captured before the first step() call: the input
    # buffer is donated below and stale handles must not be touched.
    payload = x.dtype.itemsize * x.shape[1]  # bytes contributed per device

    @partial(jax.jit, donate_argnums=(0,))
    def step(v):
        # shard_map gives the per-device view; psum is the collective under
        # test. Each call consumes the previous call's *output* (donated,
        # so the shard buffer is reused in place rather than copied):
        # iteration i+1 data-depends on iteration i, which serializes
        # dispatches on backends that run independent computations
        # concurrently (PJRT CPU) — a last-output fetch alone would let the
        # psums overlap and inflate bandwidth. The 1/n pre-scale keeps the
        # values at ~1.0 across iterations so nothing over/underflows.
        return shard_map(
            lambda s: jax.lax.psum(s * jnp.asarray(inv_n, s.dtype), "x"),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"))(v)

    state = {"v": x}

    def run(k: int) -> float:
        """Time k chained psums + a scalar fetch. A scalar fetch is the only
        synchronization barrier that holds on every PJRT backend
        (block_until_ready is a no-op on remote-tunnel platforms); the final
        output data-depends on every psum in the chain, so fetching one of
        its elements implies all k completed. The fetch round-trip is
        constant and cancels in the two-point measurement below."""
        t0 = time.perf_counter()
        v = state["v"]
        for _ in range(k):
            v = step(v)
        float(v[(0,) * v.ndim])
        state["v"] = v
        return time.perf_counter() - t0

    # Warmup covers compile (first TPU compile ~20-40s) + cache effects.
    for _ in range(max(1, warmup)):
        run(1)
    t_small, t_big = run(1), run(1 + iters)
    mean_s = max((t_big - t_small) / iters, 1e-9)

    algo_gbps = payload / mean_s / 1e9
    bus_gbps = algo_gbps * (2 * (n - 1) / n)
    return {
        "algo_gbps": algo_gbps,
        "bus_gbps": bus_gbps,
        "n_devices": float(n),
        "payload_mib": payload / (1 << 20),
        "mean_s": mean_s,
    }
