"""MoE transformer LM — the second model family (SURVEY §2.10 substrate).

A sparse-FFN sibling of ``model.TransformerLM``: same attention path (the
Pallas flash kernel with fused RoPE, flashattention.attend), but every
``moe_every``-th block swaps the dense FFN for the Switch-style top-1
expert FFN from ``moe.py``. Design is TPU-first:

- Experts shard their LEADING dim over the mesh's 'model' axis (EP rides
  the TP axis — the common deployment shape): expressed as PartitionSpecs
  under pjit, the dense one-hot dispatch/combine einsums partition cleanly
  and XLA inserts the expert all-reduce (SURVEY §2.10's `ep` axis without
  hand-written collectives).
- The router aux (load-balancing) loss joins the LM loss with a small
  weight, summed over MoE blocks inside the traced step (no Python state).
- Attention, rmsnorm, residuals, rematerialization, loss accounting and
  donation semantics are shared with the dense model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_dra.workloads import model as _dense
from tpu_dra.workloads.model import ModelConfig
from tpu_dra.workloads.moe import init_moe_params, moe_ffn

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEModelConfig(ModelConfig):
    n_experts: int = 8
    moe_every: int = 2           # block i uses MoE iff i % moe_every == 1
    capacity_factor: float = 1.25
    router_aux_weight: float = 1e-2

    def is_moe_block(self, i: int) -> bool:
        return i % self.moe_every == self.moe_every - 1


def init_params(key, cfg: MoEModelConfig) -> Params:
    """Dense-model params with MoE FFNs swapped in on MoE blocks."""
    params = _dense.init_params(key, cfg)
    keys = jax.random.split(jax.random.fold_in(key, 7), cfg.n_layers)
    for i, bp in enumerate(params["blocks"]):
        if cfg.is_moe_block(i):
            del bp["w_up"], bp["w_down"]
            bp["moe"] = init_moe_params(keys[i], cfg.d_model, cfg.d_ff,
                                        cfg.n_experts, dtype=jnp.float32)
    return params


def param_specs(cfg: MoEModelConfig) -> Params:
    """Dense specs + expert-leading-dim sharding on 'model' (EP on the TP
    axis); the router is tiny and replicated."""
    specs = _dense.param_specs(cfg)
    moe_spec = {
        "router": P(None, None),
        "w_up": P("model", None, None),
        "w_down": P("model", None, None),
    }
    for i, bs in enumerate(specs["blocks"]):
        if cfg.is_moe_block(i):
            del bs["w_up"], bs["w_down"]
            bs["moe"] = dict(moe_spec)
    return specs


def _moe_block(params, x, cfg: MoEModelConfig):
    """Attention sublayer shared with the dense block (model.py); the FFN
    half is the expert layer. Expert matmuls run in cfg.dtype (bf16 on
    the MXU fast path, like the dense FFN); routing stays fp32 inside
    moe_ffn. Returns (x, aux_loss)."""
    x = _dense.attention_sublayer(params, x, cfg)
    h = _dense._rmsnorm(x, params["ln2_scale"])
    out, aux = moe_ffn(params["moe"], h,
                       capacity_factor=cfg.capacity_factor,
                       compute_dtype=cfg.dtype)
    return x + out, aux


class MoETransformerLM:
    """Functional model: forward(params, tokens) -> (logits, aux_loss)."""

    def __init__(self, cfg: MoEModelConfig):
        self.cfg = cfg

    def forward(self, params: Params, tokens: jax.Array):
        cfg = self.cfg
        x = params["embed"].astype(cfg.dtype)[tokens]

        def wrap(fn):
            if cfg.remat == "full":
                return jax.checkpoint(fn)
            if cfg.remat == "dots":
                return jax.checkpoint(
                    fn, policy=jax.checkpoint_policies.dots_saveable)
            if cfg.remat != "none":
                raise ValueError(f"unknown remat policy {cfg.remat!r}")
            return fn

        aux_total = jnp.zeros((), jnp.float32)
        for i, bp in enumerate(params["blocks"]):
            if cfg.is_moe_block(i):
                x, aux = wrap(lambda p, v: _moe_block(p, v, cfg))(bp, x)
                aux_total = aux_total + aux
            else:
                x = wrap(lambda p, v: _dense._block(p, v, cfg))(bp, x)
        x = _dense._rmsnorm(x, jnp.ones((cfg.d_model,)))
        logits = (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)
        return logits, aux_total


def loss_fn(model: MoETransformerLM, params: Params,
            tokens: jax.Array) -> jax.Array:
    """LM cross-entropy (logsumexp form, as the dense model) plus the
    weighted router load-balancing aux."""
    logits, aux = model.forward(params, tokens[:, :-1])
    targets = tokens[:, 1:]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    nll = jnp.mean(lse - target_logit)
    return nll + model.cfg.router_aux_weight * aux


def make_train_step(model: MoETransformerLM, mesh: Mesh, lr: float = 1e-3):
    """Jitted SGD step via the shared builder (model.build_train_step);
    sharding layout mirrors the dense model's (batch on 'data', params
    per param_specs, experts on 'model')."""
    return _dense.build_train_step(model, mesh, lr, loss_fn, param_specs,
                                   MoETransformerLM)


def shard_params(params: Params, mesh: Mesh, cfg: MoEModelConfig) -> Params:
    return _dense.shard_by_specs(params, mesh, param_specs(cfg))
