"""JAX API compatibility seam for the workload library.

The workloads target the modern ``jax.shard_map`` entry point (with its
``check_vma`` flag); older runtimes (< 0.5) only ship
``jax.experimental.shard_map.shard_map`` whose equivalent flag is
``check_rep``. One adapter here keeps every workload importable and
RUNNABLE on both — the data-plane bench gates (hack/perf.sh) execute on
whatever JAX the container has, so "the collective library needs a
newer JAX" must never silently read as a driver regression.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where it exists, else the experimental entry
    point with ``check_vma`` mapped onto its older ``check_rep`` name
    (same semantics: per-shard output typing checks, disabled for
    bodies whose partials carry no varying-axis typing)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def pcast_varying(x, axis_name):
    """Mark `x` device-varying over `axis_name` under whichever
    varying-axis-typing API this JAX ships: ``jax.lax.pcast`` (0.7+),
    ``jax.lax.pvary`` (0.5-0.8, deprecated 0.9), or a no-op on
    pre-typing runtimes (where ``check_rep=False`` bodies never see
    varying-axis types at all)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_name)
    return x
