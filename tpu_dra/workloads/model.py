"""SPMD transformer LM — the flagship workload for driver-allocated slices.

The multi-node e2e benchmark pod the driver schedules (the analog of the
reference's NCCL/nvbandwidth workload images) runs this model's training
step over a `jax.sharding.Mesh` spanning the chips a ComputeDomain claim
allocated. Design is TPU-first:

- params and activations are bfloat16 on the matmul path (MXU-friendly),
  fp32 master copies only where it matters (logits/loss, optimizer state);
- sharding is expressed as `PartitionSpec`s over a ('data', 'model') mesh —
  batch/sequence on 'data' (DP + sequence sharding), hidden/heads on 'model'
  (TP). XLA inserts the all-reduce/reduce-scatter collectives over ICI;
- static shapes, `jax.checkpoint` on blocks to trade FLOPs for HBM;
- no Python control flow inside jit.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads.flashattention import attend

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 128
    dtype: Any = jnp.bfloat16
    # Attention dispatch (flashattention.attend): "auto" = pallas flash
    # kernel on TPU, jnp reference elsewhere; tests force
    # "flash_interpret" / "reference" for CPU parity checks.
    attn_impl: str = "auto"
    # Platform pin for "auto" ("tpu"/"cpu"; "" = sniff the default
    # backend). make_train_step sets this from the mesh's devices — a
    # traced forward cannot see what it runs on, and the default-backend
    # sniff is wrong for e.g. a CPU mesh on a TPU-equipped host.
    attn_platform: str = ""
    # Context parallelism: when set, the forward runs INSIDE a shard_map
    # whose activations are sequence-sharded on this mesh axis, and
    # attention crosses shards via all-to-all (ulysses.ulysses_attention;
    # sp_train.make_sp_train_step is the driver). Empty = no SP.
    seq_axis: str = ""
    # Per-block rematerialization: "none" | "dots" | "full". Measured on
    # v5e at the flagship shape (d2048/L8/S1024/B8): none -> MFU 0.647,
    # dots_saveable -> 0.596, full -> 0.536. The flash kernel's backward
    # already recomputes attention probabilities tile-wise, so full remat
    # mostly re-runs work the custom VJP re-derives anyway; flip to
    # "dots"/"full" when activations would exceed HBM (bigger models or
    # longer sequences).
    remat: str = "none"

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def _split(key, n):
    return jax.random.split(key, n)


def init_params(key, cfg: ModelConfig) -> Params:
    """Initialize fp32 params (cast to cfg.dtype inside the forward)."""
    def dense(key, shape):
        fan_in = shape[0]
        return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

    keys = _split(key, 2 + cfg.n_layers)
    params: Params = {
        "embed": jax.random.normal(keys[0], (cfg.vocab, cfg.d_model),
                                   jnp.float32) * 0.02,
        "unembed": dense(keys[1], (cfg.d_model, cfg.vocab)),
        "blocks": [],
    }
    for i in range(cfg.n_layers):
        k = _split(keys[2 + i], 6)
        params["blocks"].append({
            "ln1_scale": jnp.ones((cfg.d_model,), jnp.float32),
            "ln2_scale": jnp.ones((cfg.d_model,), jnp.float32),
            "wqkv": dense(k[0], (cfg.d_model, 3 * cfg.d_model)),
            "wo": dense(k[1], (cfg.d_model, cfg.d_model)),
            "w_up": dense(k[2], (cfg.d_model, cfg.d_ff)),
            "w_down": dense(k[3], (cfg.d_ff, cfg.d_model)),
        })
    return params


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpecs mirroring init_params: TP shards the head/ff dims on
    'model'; embeddings shard the vocab dim (row-parallel)."""
    block = {
        "ln1_scale": P(None),
        "ln2_scale": P(None),
        "wqkv": P(None, "model"),      # column-parallel QKV
        "wo": P("model", None),        # row-parallel output proj
        "w_up": P(None, "model"),      # column-parallel up-proj
        "w_down": P("model", None),    # row-parallel down-proj
    }
    return {
        "embed": P("model", None),
        "unembed": P(None, "model"),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
    }


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale.astype(x.dtype)


def attention_sublayer(params, x, cfg: ModelConfig):
    """pre-norm attention + residual; shared by the dense and MoE model
    families (moe_model._moe_block differs only in its FFN half)."""
    B, S, D = x.shape
    h = _rmsnorm(x, params["ln1_scale"])
    qkv = h @ params["wqkv"].astype(cfg.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, cfg.n_heads, cfg.d_head)
    k = k.reshape(B, S, cfg.n_heads, cfg.d_head)
    v = v.reshape(B, S, cfg.n_heads, cfg.d_head)
    # Hot op: tiled flash kernel on TPU (fwd + custom-VJP bwd, [S,S] never
    # in HBM), jnp reference elsewhere — see flashattention.attend. RoPE
    # (half-split pairing, flashattention.rope_half) is fused into the
    # attention: in-kernel on the flash path — roped q/k never touch HBM
    # (~9ms/step external at the flagship shape) — and applied externally
    # on the jnp path, so every impl computes the same function.
    if cfg.seq_axis:
        # Context parallelism: x is the LOCAL sequence block inside a
        # shard_map; attention crosses shards via all-to-all (positions
        # stay global through the re-shard, so fused RoPE is exact).
        from tpu_dra.workloads.ulysses import ulysses_attention
        ctx = ulysses_attention(
            q, k, v, axis_name=cfg.seq_axis, causal=True,
            impl=cfg.attn_impl, platform=cfg.attn_platform,
            rope=True).reshape(B, S, D)
    else:
        ctx = attend(q, k, v, causal=True, impl=cfg.attn_impl,
                     platform=cfg.attn_platform, rope=True).reshape(B, S, D)
    return x + ctx @ params["wo"].astype(cfg.dtype)


def _block(params, x, cfg: ModelConfig):
    x = attention_sublayer(params, x, cfg)
    h = _rmsnorm(x, params["ln2_scale"])
    up = jax.nn.gelu(h @ params["w_up"].astype(cfg.dtype))
    return x + up @ params["w_down"].astype(cfg.dtype)


class TransformerLM:
    """Functional model wrapper: forward(params, tokens) -> logits."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg

    def forward(self, params: Params, tokens: jax.Array) -> jax.Array:
        cfg = self.cfg
        B, S = tokens.shape
        x = params["embed"].astype(cfg.dtype)[tokens]

        block = lambda p, v: _block(p, v, cfg)  # noqa: E731
        if cfg.remat == "full":
            block = jax.checkpoint(block)
        elif cfg.remat == "dots":
            block = jax.checkpoint(
                block, policy=jax.checkpoint_policies.dots_saveable)
        elif cfg.remat != "none":
            raise ValueError(f"unknown remat policy {cfg.remat!r}")
        for bp in params["blocks"]:
            x = block(bp, x)
        x = _rmsnorm(x, jnp.ones((cfg.d_model,)))
        return (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)


def loss_fn(model: TransformerLM, params: Params, tokens: jax.Array) -> jax.Array:
    logits = model.forward(params, tokens[:, :-1])
    targets = tokens[:, 1:]
    # nll = logsumexp(logits) - logits[target]: identical math to
    # log_softmax + gather, but never stores the [B, S, V] fp32 log-prob
    # array (1GB at the flagship shape). Measured on v5e: step 187.4 ->
    # 184.2 ms, MFU 0.647 -> 0.658.
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - target_logit)


def build_train_step(model, mesh: Mesh, lr, loss, specs_fn, rebuild):
    """Shared SGD train-step builder for the model families.

    Batch (and thus sequence blocks after reshape) shard on 'data';
    parameters shard per `specs_fn(cfg)` on 'model'. Gradients reduce
    over 'data' via the psum XLA inserts for the replicated-param
    out-sharding. `loss(model, params, tokens)` is the objective;
    `rebuild(cfg)` re-instantiates the model when the config is pinned.

    ON TPU THE PARAMS ARGUMENT IS DONATED: callers must chain
    `params, loss = step(params, tokens)` and never touch the old
    params tree again — reusing it raises a donated-buffer error that
    only manifests on TPU (CPU PJRT skips donation, so CPU-tier tests
    cannot catch the misuse).
    """
    cfg = model.cfg
    from tpu_dra.workloads.flashattention import mesh_platform
    on_tpu = mesh_platform(mesh) == "tpu"
    if cfg.attn_impl == "auto" and not cfg.attn_platform:
        # Pin "auto" attention to the MESH's platform (see ModelConfig).
        cfg = dataclasses.replace(cfg,
                                  attn_platform="tpu" if on_tpu else "cpu")
        model = rebuild(cfg)
    specs = specs_fn(cfg)
    p_shard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                           is_leaf=lambda x: isinstance(x, P))
    batch_shard = NamedSharding(mesh, P("data", None))

    def step(params, tokens):
        loss_v, grads = jax.value_and_grad(
            lambda p: loss(model, p, tokens))(params)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss_v

    # Donate the incoming params: every caller chains (params, loss) =
    # step(params, ...), so the old buffers are dead and XLA can update
    # in place (2.1GB of fp32 masters at the flagship shape). CPU PJRT
    # doesn't implement donation and would warn each compile — skip there.
    donate = (0,) if on_tpu else ()
    return jax.jit(step,
                   in_shardings=(p_shard, batch_shard),
                   out_shardings=(p_shard, NamedSharding(mesh, P())),
                   donate_argnums=donate)


def make_train_step(model: TransformerLM, mesh: Mesh, lr: float = 1e-3):
    """Jitted SGD step for the dense model (see build_train_step)."""
    return build_train_step(model, mesh, lr, loss_fn, param_specs,
                            TransformerLM)


def shard_by_specs(params: Params, mesh: Mesh, specs: Params) -> Params:
    # Map over specs first: is_leaf applies to the first tree, and P must be
    # treated as a leaf (it is sequence-like and would otherwise traverse).
    return jax.tree.map(
        lambda spec, arr: jax.device_put(arr, NamedSharding(mesh, spec)),
        specs, params, is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Params, mesh: Mesh, cfg: ModelConfig) -> Params:
    return shard_by_specs(params, mesh, param_specs(cfg))
