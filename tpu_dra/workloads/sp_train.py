"""Context-parallel (sequence-sharded) training step.

The third sharding layout for the dense model, next to the DP x TP step
(model.build_train_step) and the pipeline schedule: activations shard on
the SEQUENCE axis — the layout for sequences too long for one device's
HBM — parameters replicate, and only attention crosses shards (ulysses
all-to-all inside the forward, ModelConfig.seq_axis). Gradient reduction
over the axis happens in the shard_map transpose itself (replicated-
param cotangents are summed across devices by the machinery) — the
data-parallel pattern with tokens in place of batch rows.

Objective: next-token prediction over the FULL sequence via a global
roll — targets[i] = tokens[i+1], final position masked — computed
identically by the parity reference in tests. (The DP step's shift-
by-slicing would change the per-shard lengths, which must stay equal
for the all-to-all.)

Scale note: S grows with the mesh axis, so one chip's attention work per
step grows linearly while its FFN work stays constant — the streaming XL
kernels (flashattention) keep the attention compilable at any S the HBM
can hold activations for.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import shard_map


def make_sp_train_step(model, mesh: Mesh, lr: float = 1e-3,
                       axis_name: str = "seq"):
    """Jitted SGD step over sequence-sharded tokens.

    step(params, tokens) -> (new_params, loss); params replicated,
    tokens [B, S] sharded on S (S divisible by the axis size, heads
    divisible too — the ulysses constraint). Callers must chain
    params through steps on TPU (donation, as in build_train_step).
    """
    cfg = model.cfg
    from tpu_dra.workloads.flashattention import mesh_platform
    on_tpu = mesh_platform(mesh) == "tpu"
    cfg = dataclasses.replace(
        cfg, seq_axis=axis_name,
        attn_platform=cfg.attn_platform or ("tpu" if on_tpu else "cpu"))
    sp_model = type(model)(cfg)

    # The shard_map wraps ONLY the forward, returning per-shard partial
    # sums reduced OUTSIDE; jax.grad then transposes the shard_map as a
    # whole. Computing grads INSIDE the body (grad-of-psum'd-loss plus a
    # grad psum) is the tempting formulation, but under check_vma=False
    # the unchecked psum transpose silently produces wrong gradients —
    # measured ~axis_size x off on this exact model. check_vma must stay
    # off (flash partials carry no varying-axis typing), so the body
    # stays collective-free on the loss path and correctness rests on
    # the standard shard_map transpose (replicated-param cotangents are
    # summed across devices by the machinery itself).
    def body(params, tokens, targets, mask):
        logits = sp_model.forward(params, tokens).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(
            logp, targets[..., None], axis=-1)[..., 0]
        return (jnp.sum(nll * mask)[None], jnp.sum(mask)[None])

    tok_spec = P(None, axis_name)
    fwd = shard_map(
        body, mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec, tok_spec),
        out_specs=(P(axis_name), P(axis_name)),
        check_vma=False)  # flash partials carry no varying-axis typing

    rep = NamedSharding(mesh, P())
    tok_sharding = NamedSharding(mesh, tok_spec)

    @functools.partial(jax.jit,
                       in_shardings=(rep, tok_sharding),
                       out_shardings=(rep, rep),
                       donate_argnums=(0,) if on_tpu else ())
    def step(params, tokens):
        # Global next-token objective: roll the sequence left by one and
        # mask the final position (its "target" wrapped around).
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)

        def loss_fn(p):
            sums, counts = fwd(p, tokens, targets, mask)
            return sums.sum() / jnp.maximum(counts.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params = jax.tree.map(
            lambda p, g: p - lr * g.astype(p.dtype), params, grads)
        return new_params, loss

    return step
