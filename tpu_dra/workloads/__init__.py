"""Workload-side JAX programs scheduled by the driver.

The reference ships *workload* containers that its e2e/benchmark tier runs on
driver-allocated devices: NCCL send/recv/broadcast jobs and nvbandwidth
MPIJobs (reference: tests/bats/test_cd_mnnvl_workload.bats:18-45,
demo/specs/imex/nvbandwidth-test-job-1.yaml). This package is the TPU analog:
JAX/XLA programs that consume the env the driver's CDI edits inject
(``TPU_VISIBLE_CHIPS``, slice rendezvous env) and exercise the allocated
hardware — collective bandwidth probes and an SPMD training step.

Nothing in here runs inside the driver processes; the driver is pure
control plane. These run in pods whose ResourceClaims the driver prepared.
"""

from tpu_dra.workloads.allreduce import (  # noqa: F401
    allreduce_bandwidth, device_put_sharded_uniform,
)
from tpu_dra.workloads.model import (  # noqa: F401
    ModelConfig, TransformerLM, init_params, loss_fn, make_train_step,
)
from tpu_dra.workloads.moe_model import (  # noqa: F401
    MoEModelConfig, MoETransformerLM,
)
