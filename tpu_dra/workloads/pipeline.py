"""Pipeline parallelism (`pp` of SURVEY §2.10): GPipe-style microbatch
schedule over a `stage` mesh axis.

TPU-first shape: one jit, `lax.scan` over schedule ticks (static trip
count — no data-dependent Python control flow), `lax.ppermute` moves
activations across the stage boundary each tick (rides ICI when the
stage axis is laid out along it), and per-stage weights live sharded on
the leading (stage) dimension so each device touches only its own
block's parameters.

Schedule: with S stages and M microbatches, tick t has stage s working
on microbatch (t - s) when 0 <= t - s < M; the bubble is the standard
(S - 1) / (M + S - 1) GPipe fraction.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import shard_map


def init_stage_params(key, n_stages: int, d_model: int,
                      dtype=jnp.float32):
    """One square gelu-MLP block per stage: [S, D, D]."""
    scale = 1.0 / np.sqrt(d_model)
    return (jax.random.normal(key, (n_stages, d_model, d_model),
                              jnp.float32) * scale).astype(dtype)


def stage_fn(w, x):
    """The per-stage block; swap for any (w, x) -> y computation."""
    return jax.nn.gelu(x @ w)


def pipeline_reference(weights, microbatches,
                       fn: Callable = stage_fn):
    """Sequential ground truth: run every stage over every microbatch."""
    out = microbatches
    for s in range(weights.shape[0]):
        out = jax.vmap(lambda x, w=weights[s]: fn(w, x))(out)
    return out


def make_pipeline_forward(mesh: Mesh, axis_name: str = "stage",
                          fn: Callable = stage_fn):
    """Jitted pipeline-parallel forward over `mesh`'s stage axis.

    Takes (weights [S, D, D] stage-sharded, microbatches [M, B, D]
    replicated) -> [M, B, D] outputs (replicated; produced on the last
    stage and broadcast so callers see one coherent array).
    """
    n_stages = mesh.shape[axis_name]
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def body(w, mbs):
        # w: [1, D, D] (this stage's block); mbs: [M, B, D].
        s = jax.lax.axis_index(axis_name)
        M = mbs.shape[0]
        ticks = M + n_stages - 1
        zero = jnp.zeros_like(mbs[0])

        def tick(carry, t):
            recv, outs = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            first_in = jax.lax.dynamic_index_in_dim(
                mbs, mb_idx, keepdims=False)
            x_in = jnp.where(s == 0, first_in, recv)
            active = (t >= s) & (t - s < M)
            y = jnp.where(active, fn(w[0], x_in), zero)
            # Last stage writes its finished microbatch into the output
            # accumulator; everyone else contributes zeros there.
            out_idx = jnp.clip(t - s, 0, M - 1)
            contribution = jnp.where((s == n_stages - 1) & active, y, zero)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jax.lax.dynamic_index_in_dim(outs, out_idx, keepdims=False)
                + contribution,
                out_idx, axis=0)
            # Boundary transfer: stage i's output becomes stage i+1's
            # input next tick. Stage S-1 sends nowhere; stage 0 receives
            # zeros (it reads mbs instead).
            sent = (jax.lax.ppermute(y, axis_name, fwd_perm)
                    if fwd_perm else zero)
            return (sent, outs), None

        init = (zero, jnp.zeros_like(mbs))
        (_, outs), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # Only the last stage holds real outputs; psum broadcasts them
        # (every other stage's accumulator is all zeros).
        return jax.lax.psum(outs, axis_name)

    shard = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name, None, None), P()),
        out_specs=P(),
        check_vma=False)
    return jax.jit(shard)


def shard_stage_params(weights, mesh: Mesh, axis_name: str = "stage"):
    return jax.device_put(
        weights, NamedSharding(mesh, P(axis_name, None, None)))
