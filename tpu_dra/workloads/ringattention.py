"""Ring attention: sequence-parallel causal attention over an ICI ring.

Long-context workload for driver-provisioned slices: the sequence is
sharded across the devices of a ComputeDomain slice; each device holds one
Q/K/V block and K/V blocks rotate around the ring via `jax.lax.ppermute`
(XLA lowers neighbor permutes to ICI sends), overlapping compute with the
rotation. Softmax is computed online (running max + normalizer, the
flash-attention recurrence) so no device ever materializes the full
[S, S] score matrix — memory is O(S_local * S_local) per step and the
context length scales linearly with ring size.

This is the workload-side analog of the reference's NCCL bandwidth jobs
(SURVEY §2.10): where those validate IMEX-brokered NVLink, this validates
that a driver-stitched slice sustains ring collectives. TPU-first design
notes: static shapes, `lax.fori_loop` over ring steps (no Python loop in
jit), bf16 matmuls on the MXU with fp32 accumulators for the online
softmax state.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_offset, kv_offset, causal):
    """Scores of one (q-block, kv-block) pair with causal masking in GLOBAL
    sequence coordinates. q: [B,Sq,H,D]; k,v: [B,Sk,H,D].
    Returns (scores [B,H,Sq,Sk], values v) ready for the online update."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    scores = scores.astype(jnp.float32)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    return scores


def _online_update(state, scores, v):
    """Flash-attention online-softmax accumulation step.
    state: (acc [B,H,Sq,D] f32, row_max [B,H,Sq] f32, denom [B,H,Sq] f32).
    """
    acc, row_max, denom = state
    block_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(row_max, block_max)
    correction = jnp.exp(row_max - new_max)
    p = jnp.exp(scores - new_max[..., None])  # [B,H,Sq,Sk] f32
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    acc = acc * correction[..., None] + pv
    denom = denom * correction + jnp.sum(p, axis=-1)
    return acc, new_max, denom


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True):
    """Per-device body (inside shard_map): q,k,v are the LOCAL sequence
    blocks [B, S_local, H, D]. K/V rotate ring-wise; every device sees all
    blocks after axis_size steps."""
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    q_offset = my_index * s_local

    # pcast to varying: the fresh carries are device-invariant but the
    # loop produces device-varying values; shard_map's typed carries must
    # agree. (jax.lax.pvary is deprecated as of jax 0.9.)
    def _varying(x):
        return jax.lax.pcast(x, axis_name, to="varying")

    acc = _varying(jnp.zeros((b, h, s_local, d), jnp.float32))
    row_max = _varying(jnp.full((b, h, s_local), NEG_INF, jnp.float32))
    denom = _varying(jnp.zeros((b, h, s_local), jnp.float32))

    def step(i, carry):
        acc, row_max, denom, k_blk, v_blk = carry
        # Block i arrived from neighbor (my_index + i) mod axis_size.
        kv_index = (my_index + i) % axis_size
        scores = _block_attend(q, k_blk, v_blk, q_offset,
                               kv_index * s_local, causal)
        acc, row_max, denom = _online_update((acc, row_max, denom),
                                             scores, v_blk)
        # Rotate K/V one hop around the ring (device p -> p-1, so the
        # NEXT step sees the block of my_index+i+1). The final rotation
        # is redundant but keeps the loop body uniform for the compiler.
        perm = [(p, (p - 1) % axis_size) for p in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return acc, row_max, denom, k_blk, v_blk

    acc, row_max, denom, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (acc, row_max, denom, k, v))
    out = acc / denom[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Sq,H,D]


def make_ring_attention(mesh: Mesh, axis_name: str = "data",
                        causal: bool = True):
    """Jitted sequence-parallel attention over `mesh`'s `axis_name` axis.
    Inputs/outputs [B, S, H, D] sharded on S."""
    seq_sharding = NamedSharding(mesh, P(None, axis_name, None, None))
    spec = P(None, axis_name, None, None)

    body = functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return jax.jit(fn, in_shardings=(seq_sharding,) * 3,
                   out_shardings=seq_sharding)


def reference_attention(q, k, v, causal: bool = True):
    """Unsharded attention for correctness checks."""
    d = q.shape[-1]
    scores = (jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
              ).astype(jnp.float32)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
        scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)
