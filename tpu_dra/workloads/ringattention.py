"""Ring attention: sequence-parallel causal attention over an ICI ring.

Long-context workload for driver-provisioned slices: the sequence is
sharded across the devices of a ComputeDomain slice; each device holds one
Q/K/V block and K/V blocks rotate around the ring via `jax.lax.ppermute`
(XLA lowers neighbor permutes to ICI sends), overlapping compute with the
rotation. Softmax is computed online (running max + normalizer, the
flash-attention recurrence) so no device ever materializes the full
[S, S] score matrix — memory is O(S_local * S_local) per step and the
context length scales linearly with ring size.

This is the workload-side analog of the reference's NCCL bandwidth jobs
(SURVEY §2.10): where those validate IMEX-brokered NVLink, this validates
that a driver-stitched slice sustains ring collectives. TPU-first design
notes: static shapes, `lax.fori_loop` over ring steps (no Python loop in
jit), bf16 matmuls on the MXU with fp32 accumulators for the online
softmax state.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import pcast_varying, shard_map

NEG_INF = -1e30


def _jnp_partial(q, k, v, causal):
    """(out [B,Sq,H,D], lse [B,H,Sq]) of q against one K/V block, plain
    jnp (the CPU-mesh / odd-shape path). lse is over scaled scores —
    flash_attention_with_lse's convention, so partials merge either way."""
    d = q.shape[-1]
    scores = (jnp.einsum("bqhd,bkhd->bhqk", q, k)
              / math.sqrt(d)).astype(jnp.float32)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - block_max[..., None])
    denom = jnp.sum(p, axis=-1)
    lse = block_max + jnp.log(jnp.maximum(denom, 1e-30))
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / denom[..., None]).astype(v.dtype),
                     v).astype(q.dtype)
    return out, lse


def _flash_partial(q, k, v, causal, interpret):
    from tpu_dra.workloads.flashattention import flash_attention_with_lse
    # Explicit block size that divides s_local: the past-block case is
    # non-causal, which cannot be zero-padded, and the kernel's default
    # block (256) does not divide every lane-aligned length (e.g. 384).
    s = q.shape[1]
    blk = 256 if s % 256 == 0 else 128
    return flash_attention_with_lse(q, k, v, causal=causal,
                                    block_q=min(blk, s), block_k=min(blk, s),
                                    interpret=interpret)


def _ring_flash_ok(s_local: int, d: int) -> bool:
    """Flash per-step partials need a block size dividing s_local (the
    past-block case is non-causal, which cannot be zero-padded)."""
    return s_local % 128 == 0 and d >= 8


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   impl: str = "auto", platform: str = ""):
    """Per-device body (inside shard_map): q,k,v are the LOCAL sequence
    blocks [B, S_local, H, D]. K/V rotate ring-wise; every device sees all
    blocks after axis_size steps.

    Each ring step computes a PARTIAL softmax attention of the local Q
    against the visiting K/V block — three statically-shaped cases (the
    visiting block is entirely in the future / on the diagonal / entirely
    in the past, so the causal structure never depends on traced offsets)
    — and partials merge by their logsumexp:
        new_lse = logaddexp(acc_lse, lse_b)
        acc_o   = acc_o * e^(acc_lse - new_lse) + o_b * e^(lse_b - new_lse)
    With impl="flash" the per-step partial is the pallas kernel
    (flash_attention_with_lse, joint (out, lse) VJP), so no device ever
    materializes even the LOCAL [S_local, S_local] score matrix — memory
    is O(block) and context length scales with ring size times what one
    chip's flash kernel handles.

    impl: "auto" (flash on TPU when shapes allow), "flash",
    "flash_interpret" (CPU-testable), "jnp".
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    if impl == "auto":
        # `platform` is the caller's statement of what the mesh runs on
        # (make_ring_attention passes it from mesh.devices); the default-
        # backend sniff is only the fallback for direct callers.
        if not platform:
            from tpu_dra.workloads.flashattention import default_platform
            platform = default_platform()
        use_flash = platform == "tpu" and _ring_flash_ok(s_local, d)
        interpret = False
    elif impl in ("flash", "flash_interpret"):
        if not _ring_flash_ok(s_local, d):
            raise ValueError(
                "flash ring needs s_local % 128 == 0 and head dim >= 8 "
                f"(got s_local={s_local}, d={d})")
        use_flash = True
        interpret = impl == "flash_interpret"
    elif impl == "jnp":
        use_flash, interpret = False, False
    else:
        raise ValueError(f"unknown ring attention impl {impl!r}")

    def partial_fn(cs):
        if use_flash:
            return lambda qq, kk, vv: _flash_partial(qq, kk, vv, cs,
                                                     interpret)
        return lambda qq, kk, vv: _jnp_partial(qq, kk, vv, cs)

    def future_fn(qq, kk, vv):
        # Visiting block is entirely in the future: contributes nothing.
        # (o=0, lse=NEG_INF) is the identity of the logsumexp merge.
        # The lse constant needs an explicit pcast: switch branches must
        # agree on varying-axis typing and the real branches' lse is
        # device-varying (zeros_like(qq) already inherits qq's typing).
        return (jnp.zeros_like(qq),
                pcast_varying(jnp.full((b, h, s_local), NEG_INF,
                                       jnp.float32), axis_name))

    branches = [future_fn, partial_fn(True), partial_fn(False)]

    # pcast to varying: the fresh carries are device-invariant but the
    # loop produces device-varying values; shard_map's typed carries must
    # agree (no-op on pre-typing runtimes — see _compat.pcast_varying).
    def _varying(x):
        return pcast_varying(x, axis_name)

    acc_o = _varying(jnp.zeros((b, s_local, h, d), jnp.float32))
    acc_lse = _varying(jnp.full((b, h, s_local), NEG_INF, jnp.float32))

    def step(i, carry):
        acc_o, acc_lse, k_blk, v_blk = carry
        # Block i arrived from neighbor (my_index + i) mod axis_size.
        kv_index = (my_index + i) % axis_size
        if causal:
            # 0: future (kv > my), 1: diagonal (causal within the block),
            # 2: past (fully visible).
            case = jnp.where(kv_index > my_index, 0,
                             jnp.where(kv_index == my_index, 1, 2))
        else:
            case = jnp.int32(2)
        o_b, lse_b = jax.lax.switch(case, branches, q, k_blk, v_blk)

        # Merge partials by logsumexp weight. NEG_INF is a FINITE
        # sentinel (-1e30): (-1e30) - (-1e30) stays 0, so the
        # before-first-contribution merges are NaN-free by construction.
        new_lse = jnp.logaddexp(acc_lse, lse_b)
        w_old = jnp.exp(acc_lse - new_lse)
        w_new = jnp.exp(lse_b - new_lse)
        to_bshd = lambda w: jnp.transpose(w, (0, 2, 1))[..., None]  # noqa: E731
        acc_o = (acc_o * to_bshd(w_old)
                 + o_b.astype(jnp.float32) * to_bshd(w_new))
        acc_lse = new_lse

        # Rotate K/V one hop around the ring (device p -> p-1, so the
        # NEXT step sees the block of my_index+i+1). The final rotation
        # is redundant but keeps the loop body uniform for the compiler.
        perm = [(p, (p - 1) % axis_size) for p in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return acc_o, acc_lse, k_blk, v_blk

    acc_o, acc_lse, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (acc_o, acc_lse, k, v))
    return acc_o.astype(q.dtype)  # [B,Sq,H,D]


def make_ring_attention(mesh: Mesh, axis_name: str = "data",
                        causal: bool = True, impl: str = "auto"):
    """Jitted sequence-parallel attention over `mesh`'s `axis_name` axis.
    Inputs/outputs [B, S, H, D] sharded on S."""
    seq_sharding = NamedSharding(mesh, P(None, axis_name, None, None))
    spec = P(None, axis_name, None, None)

    # Resolve "auto" against the MESH's devices, not the default backend:
    # a CPU mesh on a TPU-equipped host must not pick the Mosaic kernel.
    from tpu_dra.workloads.flashattention import mesh_platform
    on_tpu = mesh_platform(mesh) == "tpu"
    body = functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal, impl=impl,
                             platform="tpu" if on_tpu else "cpu")
    # check_vma=False: pallas_call results carry no varying-axis typing
    # (their ShapeDtypeStructs would need explicit vma), so the typed-
    # carry check cannot see through the flash per-step partials.
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return jax.jit(fn, in_shardings=(seq_sharding,) * 3,
                   out_shardings=seq_sharding)


def reference_attention(q, k, v, causal: bool = True):
    """Unsharded attention for correctness checks."""
    d = q.shape[-1]
    scores = (jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
              ).astype(jnp.float32)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
        scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)
