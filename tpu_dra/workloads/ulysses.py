"""All-to-all (Ulysses-style) sequence parallelism.

The second sequence-parallel strategy next to ring attention
(ringattention.py): instead of rotating K/V blocks around a ring for
`axis_size` partial-softmax steps, two `all_to_all` collectives re-shard
the activations from sequence-sharded [B, S/N, H, D] to HEAD-sharded
[B, S, H/N, D], run exact full-sequence attention per head subset (the
pallas flash kernel on TPU — including its streaming XL path when S
exceeds the resident VMEM budget), and shard back.

Trade-offs vs ring (both are first-class; pick per topology):
- collectives: 3 all_to_alls in + 1 out, each moving the full activation
  once — vs ring's N ppermute steps. On all-to-all-friendly fabrics (ICI
  torus) this is fewer, larger transfers with no per-step latency chain.
- constraint: heads must divide by the mesh axis (H % N == 0); ring has
  no head constraint and composes with any H.
- attention math: exact full-S attention per device (positions are
  global, so fused in-kernel RoPE applies directly); ring must merge
  partials by logsumexp and apply RoPE outside the kernel.

Reference frame: the reference repo has no SP of any kind (SURVEY §2.10
— it provides the ComputeDomain substrate these strategies run on);
this is TPU-first long-context machinery for the workloads the driver
provisions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dra.workloads._compat import shard_map


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      impl: str = "auto", platform: str = "",
                      rope: bool = False):
    """Per-device body (inside shard_map): q, k, v are LOCAL sequence
    blocks [B, S/N, H, D] with H divisible by the axis size. Returns the
    local sequence block of the exact attention output."""
    from tpu_dra.workloads.flashattention import attend

    axis_size = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    if h % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads % axis_size == 0 (H={h}, N={axis_size})")

    def to_heads(x):
        # [B, S/N, H, D] -> [B, S, H/N, D]: split the head axis across
        # the mesh, gather the sequence axis.
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # Exact attention over the FULL sequence for this device's head
    # subset; positions are global so in-kernel RoPE applies as-is.
    out = attend(qh, kh, vh, causal=causal, impl=impl, platform=platform,
                 rope=rope)
    # [B, S, H/N, D] -> [B, S/N, H, D]: scatter sequence, gather heads.
    return jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def make_ulysses_attention(mesh: Mesh, axis_name: str = "seq",
                           causal: bool = True, impl: str = "auto",
                           rope: bool = False):
    """Jitted all-to-all sequence-parallel attention over `mesh`'s
    `axis_name` axis. Inputs/outputs [B, S, H, D] sharded on S; H must
    divide by the axis size (checked at trace time)."""
    seq_sharding = NamedSharding(mesh, P(None, axis_name, None, None))
    spec = P(None, axis_name, None, None)

    # Resolve "auto" against the MESH's devices, not the default backend
    # (same contract as make_ring_attention).
    from tpu_dra.workloads.flashattention import mesh_platform
    on_tpu = mesh_platform(mesh) == "tpu"
    body = functools.partial(ulysses_attention, axis_name=axis_name,
                             causal=causal, impl=impl, rope=rope,
                             platform="tpu" if on_tpu else "cpu")
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return jax.jit(fn, in_shardings=(seq_sharding,) * 3,
                   out_shardings=seq_sharding)
