"""Shared multi-node ComputeDomain harness for tests and bench.

One "node" = a CD kubelet plugin (ComputeDomainManager + DeviceState +
CDDriver) plus, once the node is labeled, a DaemonRunner wrapping the real
C++ slice daemon. Used by tests/test_cd_integration.py and bench.py so the
wiring lives in exactly one place.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from tpu_dra.api import types as apitypes
from tpu_dra.cddaemon.main import DaemonRunner, flags as daemon_flags
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.cdplugin.computedomain import ComputeDomainManager
from tpu_dra.cdplugin.device_state import DeviceState
from tpu_dra.cdplugin.driver import CDDriver
from tpu_dra.k8s import NODES
from tpu_dra.tpuplugin.checkpoint import CheckpointManager

CD_CDI_VENDOR = "k8s.compute-domain.tpu.dev"

DAEMON_BIN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "build", "tpu-slice-daemon")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def read_claim_env(cdi, claim_uid: str) -> dict:
    """The workload container's env view of a prepared claim, parsed
    from the WRITTEN CDI spec (the same file kubelet's runtime
    consumes). One decoding for every harness, so a CDI-env encoding
    change cannot silently diverge between them."""
    spec = cdi.read_spec(cdi.claim_spec_path(claim_uid))
    return dict(e.split("=", 1)
                for e in spec["devices"][0]["containerEdits"]["env"])


class FakeNode:
    """One 'node': a CD kubelet plugin plus (once labeled) a cd daemon."""

    def __init__(self, cluster, name: str, tmp_path, *,
                 slice_id: str = "slice-A", retry_timeout: float = 20.0,
                 daemon_bin: str = DAEMON_BIN):
        self.cluster = cluster
        self.name = name
        self.tmp = tmp_path / name if hasattr(tmp_path, "__truediv__") \
            else _PathShim(os.path.join(str(tmp_path), name))
        self._daemon_bin = daemon_bin
        cluster.create(NODES, {"apiVersion": "v1", "kind": "Node",
                               "metadata": {"name": name}})
        self.cd_manager = ComputeDomainManager(
            cluster, node_name=name,
            driver_plugin_dir=str(self.tmp / "plugin"))
        self.cd_manager.start()
        self.cdi = CDIHandler(str(self.tmp / "cdi"), vendor=CD_CDI_VENDOR)
        self.state = DeviceState(
            cd_manager=self.cd_manager, cdi=self.cdi,
            checkpoints=CheckpointManager(str(self.tmp / "plugin")),
            driver_name=apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
            node_name=name, slice_id=slice_id)
        self.driver = CDDriver(
            state=self.state, client=cluster,
            driver_name=apitypes.COMPUTE_DOMAIN_DRIVER_NAME, node_name=name,
            slice_id=slice_id, plugin_dir=str(self.tmp / "plugin"),
            retry_timeout=retry_timeout)
        self.driver.start()
        self.daemon: Optional[DaemonRunner] = None

    def wait_labeled(self, cd_uid: str, timeout: float = 20.0) -> bool:
        return self.cluster.wait_for(
            lambda: (self.cluster.get(NODES, self.name)["metadata"]
                     .get("labels") or {}).get(
                apitypes.COMPUTE_DOMAIN_LABEL_KEY) == cd_uid,
            timeout=timeout)

    def start_daemon(self, cd) -> None:
        """The DaemonSet-pod analog, started when the node is labeled."""
        ns = daemon_flags().parse([
            "--cd-uid", cd["metadata"]["uid"],
            "--cd-name", cd["metadata"]["name"],
            "--cd-namespace", cd["metadata"]["namespace"],
            "--node-name", self.name, "--pod-ip", "127.0.0.1",
            "--port", str(free_port()),
            "--work-dir", str(self.tmp / "daemon"),
            "--hosts-file", str(self.tmp / "hosts"),
            "--daemon-binary", self._daemon_bin,
        ])
        self.daemon = DaemonRunner(self.cluster, ns)
        self.daemon.start()

    def stop(self) -> None:
        if self.daemon:
            self.daemon.stop()
            self.daemon = None
        self.driver.shutdown()
        self.cd_manager.stop()


COORDINATOR_BIN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "build",
    "tpu-multiprocess-coordinator")


class CoordinatorNodeSim:
    """Plays kubelet for multiprocess-coordinator Deployments.

    Watches the cluster for Deployments labeled
    ``app.kubernetes.io/name=tpu-multiprocess-daemon`` (the ones
    MultiprocessDaemon.start creates), runs the REAL
    tpu-multiprocess-coordinator binary with the pod's command — hostPath
    volume substituted for /multiprocess — and flips readyReplicas to 1
    only once the binary's own ``--check`` probe returns READY. Readiness
    therefore comes from the actual process lifecycle, exactly as it would
    from kubelet's exec probes in a real cluster; nothing is fabricated.
    On Deployment deletion the process is terminated (kubelet reaping the
    pod). Used by the multiprocess e2e tier and the cluster-tier e2e.
    """

    def __init__(self, cluster, namespace: str,
                 binary: str = COORDINATOR_BIN, interval: float = 0.05):
        self._cluster = cluster
        self._namespace = namespace
        self._binary = binary
        self._interval = interval
        self.processes = {}  # deployment name -> subprocess.Popen
        self.errors = {}     # deployment name -> repr of last loop error
        self._host_dirs = {}
        self._stop = None
        self._thread = None

    def start(self) -> None:
        import threading
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop:
            self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for proc in self.processes.values():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except Exception:  # noqa: BLE001
                    proc.kill()
        self.processes.clear()

    def host_dir(self, deployment_name: str) -> Optional[str]:
        return self._host_dirs.get(deployment_name)

    # -- kubelet loop -------------------------------------------------------

    def _run(self) -> None:
        import subprocess
        from tpu_dra.k8s import DEPLOYMENTS
        sel = "app.kubernetes.io/name=tpu-multiprocess-daemon"
        while not self._stop.wait(self._interval):
            try:
                deps = self._cluster.list(DEPLOYMENTS, self._namespace,
                                          label_selector=sel)
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[fake cluster shutting down mid-tick; the loop exits on the next stop wait]
                continue
            seen = set()
            for dep in deps:
                name = dep["metadata"]["name"]
                seen.add(name)
                # Per-deployment errors (unbuildable binary, bad pod spec)
                # must not kill the kubelet loop: record them so the test's
                # eventual ready-timeout has a cause to point at.
                try:
                    proc = self.processes.get(name)
                    if proc is None:
                        self._launch(dep, subprocess)
                    elif proc.poll() is None:
                        self._set_ready(dep, self._probe(dep, subprocess))
                    else:
                        # Process died (e.g. test killed it): not ready.
                        # The Deployment controller would restart it; tests
                        # assert on the unready window, so we do not.
                        self._set_ready(dep, False)
                except Exception as e:  # noqa: BLE001
                    self.errors[name] = repr(e)
            # Deployment gone -> kubelet reaps the pod.
            for name in list(self.processes):
                if name not in seen:
                    proc = self.processes.pop(name)
                    self._host_dirs.pop(name, None)
                    if proc.poll() is None:
                        proc.terminate()
                        try:
                            proc.wait(timeout=3)
                        except Exception:  # noqa: BLE001
                            proc.kill()

    def _pod_spec(self, dep):
        return ((dep.get("spec") or {}).get("template") or {}).get("spec") or {}

    def _launch(self, dep, subprocess_mod) -> None:
        spec = self._pod_spec(dep)
        host_dir = None
        for vol in spec.get("volumes", []):
            if vol.get("name") == "coord":
                host_dir = (vol.get("hostPath") or {}).get("path")
        container = (spec.get("containers") or [{}])[0]
        command = list(container.get("command") or [])
        if not host_dir or not command:
            return
        # kubelet's bind mount: the container sees /multiprocess, the host
        # side is the claim's coordination dir.
        argv = [self._binary] + [
            host_dir if a == "/multiprocess" else a for a in command[1:]]
        name = dep["metadata"]["name"]
        self._host_dirs[name] = host_dir
        self.processes[name] = subprocess_mod.Popen(
            argv, stdout=subprocess_mod.DEVNULL,
            stderr=subprocess_mod.DEVNULL)

    def _probe(self, dep, subprocess_mod) -> bool:
        host_dir = self._host_dirs.get(dep["metadata"]["name"])
        if not host_dir:
            return False
        res = subprocess_mod.run(
            [self._binary, "--check", "--dir", host_dir],
            stdout=subprocess_mod.DEVNULL, stderr=subprocess_mod.DEVNULL)
        return res.returncode == 0

    def _set_ready(self, dep, ready: bool) -> None:
        from tpu_dra.k8s import DEPLOYMENTS
        want = 1 if ready else 0
        if (dep.get("status") or {}).get("readyReplicas", 0) == want:
            return
        dep = dict(dep)
        dep.setdefault("status", {})["readyReplicas"] = want
        try:
            self._cluster.update_status(DEPLOYMENTS, dep, self._namespace)
        except Exception:  # noqa: BLE001 # drflow: swallow-ok[optimistic status write lost an RV race; the next kubelet tick retries]
            pass


class _PathShim:
    """Minimal pathlib-like '/'-join for plain-string tmp dirs (bench)."""

    def __init__(self, path: str):
        self._path = path

    def __truediv__(self, other: str) -> "_PathShim":
        return _PathShim(os.path.join(self._path, other))

    def __str__(self) -> str:
        return self._path


class FakeKernelPci:
    """Simulates the kernel's PCI bind/unbind semantics over a fake sysfs
    tree (make_fake_sysfs + _materialize_pci): a background thread consumes
    writes to the per-driver bind/unbind files and moves the per-device
    `driver` symlinks accordingly, honoring driver_override the way the
    real bus match does. This lets PassthroughManager run its REAL file
    protocol end-to-end in tests — the rebind only 'takes' if the manager
    wrote the exact files the kernel ABI requires."""

    DRIVERS = ("tpu-accel", "vfio-pci")

    def __init__(self, root: str, tick: float = 0.005):
        import threading as _threading
        self._root = root.rstrip("/")
        self._tick = tick
        self._stop = _threading.Event()
        self._thread: Optional[object] = None

    def start(self) -> "FakeKernelPci":
        import threading as _threading
        self._thread = _threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def step(self) -> None:
        """Synchronously process pending bind/unbind writes once."""
        for drv in self.DRIVERS:
            self._process_unbind(drv)
        for drv in self.DRIVERS:
            self._process_bind(drv)

    # -- internals ----------------------------------------------------------

    def _run(self) -> None:
        import time as _time
        while not self._stop.is_set():
            self.step()
            _time.sleep(self._tick)

    def _driver_dir(self, drv: str) -> str:
        return os.path.join(self._root, "sys", "bus", "pci", "drivers", drv)

    def _device_dir(self, addr: str) -> str:
        return os.path.join(self._root, "sys", "bus", "pci", "devices", addr)

    def _consume(self, path: str) -> str:
        try:
            with open(path, "r+") as f:
                content = f.read().strip()
                f.seek(0)
                f.truncate()
            return content
        except OSError:
            return ""

    def _process_unbind(self, drv: str) -> None:
        addr = self._consume(os.path.join(self._driver_dir(drv), "unbind"))
        if not addr:
            return
        link = os.path.join(self._device_dir(addr), "driver")
        try:
            if os.path.basename(os.readlink(link)) == drv:
                os.unlink(link)
        except OSError:
            pass  # not bound: kernel would EINVAL; fake tolerates

    def _process_bind(self, drv: str) -> None:
        addr = self._consume(os.path.join(self._driver_dir(drv), "bind"))
        if not addr:
            return
        ddir = self._device_dir(addr)
        link = os.path.join(ddir, "driver")
        if os.path.islink(link):
            return  # already bound somewhere: kernel refuses double-bind
        try:
            with open(os.path.join(ddir, "driver_override")) as f:
                override = f.read().strip()
        except OSError:
            override = ""
        # Kernel match rules: an override must name this driver; without
        # an override only the native accel driver matches the device id.
        if override:
            if override != drv:
                return
        elif drv != "tpu-accel":
            return
        os.symlink(self._driver_dir(drv), link)


def provision_two_node_cd(namespace: str = "cdtest",
                          node_names=("node-a", "node-b"),
                          retry_timeout: float = 30.0,
                          join_timeout: float = 60.0) -> dict:
    """The historical 2-node entry point (bench.bench_cd_convergence,
    __graft_entry__._cd_psum_probe); provision_multi_node_cd is the
    general N-node harness."""
    return provision_multi_node_cd(namespace=namespace,
                                   node_names=node_names,
                                   retry_timeout=retry_timeout,
                                   join_timeout=join_timeout)


def provision_multi_node_cd(n_nodes: int = 2, namespace: str = "cdtest",
                            node_names=None,
                            retry_timeout: float = 30.0,
                            join_timeout: float = 60.0) -> dict:
    """Provision an N-node ComputeDomain through the full CD stack —
    controller + CD kubelet plugins + real C++ slice daemons converging
    over the fake API server — and prepare one workload channel claim per
    node (SURVEY §3.3). The single source of the harness for
    bench.bench_cd_convergence (convergence timing) and
    __graft_entry__._cd_psum_probe (claim-env -> mesh -> collective);
    sized beyond 2 nodes for the data-plane tier (SURVEY §17).

    Returns {"ok", "error"/"skipped", "elapsed_s", "envs"} where
    elapsed_s is CD-creation -> all claims prepared, and envs maps node
    name -> the prepared claim's CDI env (the workload container's view:
    TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, coordinator/megascale vars).
    """
    import shutil
    import tempfile
    import threading
    import time

    from tpu_dra.cdcontroller import Controller
    from tpu_dra.k8s import COMPUTEDOMAINS, FakeCluster, RESOURCECLAIMS
    from tpu_dra.kubeletplugin.server import Claim

    if node_names is None:
        node_names = tuple(f"node-{i:02d}" for i in range(n_nodes))
    if not os.path.exists(DAEMON_BIN):
        return {"ok": False, "skipped": "native daemon not built"}

    # Fake chip inventory is deliberate: this harness benchmarks/validates
    # the control plane with simulated nodes, and the hardened auto-detect
    # would refuse fake-on-real-hardware.
    saved = os.environ.get("TPU_DRA_TPUINFO_BACKEND")
    os.environ["TPU_DRA_TPUINFO_BACKEND"] = "fake"
    tmp = tempfile.mkdtemp(prefix="tpu-dra-cd2-")
    controller = None
    nodes = []
    try:
        cluster = FakeCluster()
        controller = Controller(cluster, namespace="tpu-dra-driver",
                                image="harness", gc_interval=3600.0)
        controller.start()
        nodes = [FakeNode(cluster, name, tmp, retry_timeout=retry_timeout)
                 for name in node_names]

        t0 = time.perf_counter()
        cd = cluster.create(COMPUTEDOMAINS, {
            "apiVersion": apitypes.API_VERSION, "kind": "ComputeDomain",
            "metadata": {"name": "harness-cd", "namespace": namespace},
            "spec": {"numNodes": len(nodes), "channel": {
                "resourceClaimTemplate": {"name": "harness-rct"}}},
        })
        results: dict = {}
        envs: dict = {}

        def kubelet(node):
            claim = cluster.create(RESOURCECLAIMS, {
                "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
                "metadata": {"name": f"w-{node.name}",
                             "namespace": namespace},
                "spec": {"devices": {"requests": [{"name": "r0"}]}},
                "status": {"allocation": {"devices": {
                    "results": [{
                        "request": "r0",
                        "driver": apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
                        "pool": node.name, "device": "channel-0"}],
                    "config": [{"requests": ["r0"], "opaque": {
                        "driver": apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
                        "parameters": {
                            "apiVersion": apitypes.API_VERSION,
                            "kind": "ComputeDomainChannelConfig",
                            "domainID": cd["metadata"]["uid"],
                            "allocationMode": "Single"}}}]}}},
            })
            uid = claim["metadata"]["uid"]
            c = Claim(uid=uid, name=claim["metadata"]["name"],
                      namespace=namespace)
            results[node.name] = node.driver.prepare_claims([c])[c.uid]
            envs[node.name] = read_claim_env(node.cdi, uid)

        threads = [threading.Thread(target=kubelet, args=(n,))
                   for n in nodes]
        for t in threads:
            t.start()
        failure = None
        # Play the DaemonSet: start a daemon when its node gets labeled.
        for node in nodes:
            if not node.wait_labeled(cd["metadata"]["uid"]):
                failure = f"{node.name} never labeled"
                break
            node.start_daemon(cd)
        for t in threads:
            t.join(timeout=join_timeout)
        elapsed = time.perf_counter() - t0
        if failure is None and any(t.is_alive() for t in threads):
            failure = "kubelet prepare threads timed out"
        if failure is None:
            errors = [f"{n}: {r.error}"
                      for n, r in results.items() if r.error]
            if errors or len(envs) != len(nodes):
                failure = "; ".join(errors) or "prepare incomplete"
        if failure:
            # Drain the prepare retry loops (bounded by retry_timeout)
            # before teardown rips the state dirs out from under them.
            for t in threads:
                t.join(timeout=retry_timeout + 5)
            return {"ok": False, "error": failure}
        return {"ok": True, "elapsed_s": elapsed, "envs": envs}
    finally:
        for node in nodes:
            node.stop()
        if controller is not None:
            controller.stop()
        shutil.rmtree(tmp, ignore_errors=True)
        if saved is None:
            os.environ.pop("TPU_DRA_TPUINFO_BACKEND", None)
        else:
            os.environ["TPU_DRA_TPUINFO_BACKEND"] = saved


# ---------------------------------------------------------------------------
# Scheduler-churn inventory (shared by bench.bench_sched_churn, the
# chaos SchedulerChaosHarness, and tests/test_scheduler_scale.py)
# ---------------------------------------------------------------------------

DEFAULT_SCHED_SELECTOR = ('device.driver == "tpu.dev" && '
                          'device.attributes["tpu.dev"].type == "chip"')


def seed_sched_inventory(client, *, nodes: int, chips_per_node: int,
                         node_fmt: str = "n{i}",
                         selector_exprs=None,
                         generation: str = "v5p",
                         namespace: str = "default",
                         hosts_per_slice: int = 1,
                         claim_counts=()):
    """Seed the control-plane churn fixture in ONE place: DeviceClass
    ``tpu.dev`` (CEL selectors), ResourceClaimTemplate ``tmpl``, and
    `nodes` Nodes each publishing a ResourceSlice of `chips_per_node`
    whole-chip devices with the full topology attribute set (type,
    generation, coordX/Y/Z, sliceTopology, sliceID, workerIndex —
    coords from the same per-generation layout the fake backend
    publishes). Returns the node names. `hosts_per_slice` groups
    consecutive nodes into one physical ICI slice (shared sliceID,
    workerIndex 0..h-1); `claim_counts` additionally creates a
    ``tmpl<n>`` ResourceClaimTemplate requesting n devices for each n.
    A schema change here changes bench, chaos, and tests together
    instead of drifting across three hand-copied fixtures."""
    from tpu_dra.k8s.resources import (
        DEVICECLASSES, NODES, RESOURCECLAIMTEMPLATES, RESOURCESLICES,
    )
    from tpu_dra.native.tpuinfo import default_fake_chips

    exprs = (list(selector_exprs) if selector_exprs
             else [DEFAULT_SCHED_SELECTOR])
    client.create(DEVICECLASSES, {
        "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
        "metadata": {"name": "tpu.dev"},
        "spec": {"selectors": [{"cel": {"expression": e}} for e in exprs]}})
    for count in (None,) + tuple(claim_counts):
        req = {"name": "tpu", "exactly": {"deviceClassName": "tpu.dev"}}
        if count is not None:
            req["exactly"]["count"] = count
        client.create(RESOURCECLAIMTEMPLATES, {
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaimTemplate",
            "metadata": {"name": "tmpl" if count is None else f"tmpl{count}",
                         "namespace": namespace},
            "spec": {"spec": {"devices": {"requests": [req]}}},
        }, namespace=namespace)
    names = []
    for i in range(nodes):
        name = node_fmt.format(i=i)
        names.append(name)
        chips = default_fake_chips(
            chips_per_node, generation,
            slice_id=f"ici-{i // hosts_per_slice}",
            worker_index=i % hosts_per_slice,
            total_workers=hosts_per_slice)
        client.create(NODES, {"apiVersion": "v1", "kind": "Node",
                              "metadata": {"name": name, "labels": {}}})
        client.create(RESOURCESLICES, {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
            "metadata": {"name": f"{name}-tpu.dev"},
            "spec": {"driver": "tpu.dev", "nodeName": name,
                     "pool": {"name": name, "generation": 1},
                     "devices": [{"name": f"chip-{c.index}", "attributes": {
                         "type": {"string": "chip"},
                         "generation": {"string": generation},
                         "coordX": {"int": c.coords[0]},
                         "coordY": {"int": c.coords[1]},
                         "coordZ": {"int": c.coords[2]},
                         "sliceTopology": {"string": c.slice_topology},
                         "sliceID": {"string": c.slice_id},
                         "workerIndex": {"int": c.worker_index}}}
                         for c in chips]}})
    return names


# ---------------------------------------------------------------------------
# Fake multi-host slice provisioning (data-plane tier, SURVEY §17)
# ---------------------------------------------------------------------------

class MeshSliceHarness:
    """A fake multi-host TPU slice provisioned through the REAL
    tpuplugin prepare pipeline, for the data-plane bench/tests: each of
    `n_workers` "hosts" runs its own DeviceState + CDIHandler +
    CheckpointManager over a FakeBackend holding that worker's block of
    the GLOBAL slice coordinate space (default_fake_chips with
    worker_index/total_workers), claims are prepared through
    ``DeviceState.prepare_batch`` (the same pipeline kubelet drives),
    and each claim's env is read back from the WRITTEN CDI spec — the
    workload container's view, including the exported topology block
    (TPU_CHIP_COORDS / TPU_SLICE_TOPOLOGY) — merged with the
    cddaemon-shaped worker identity the CD channel claim would add
    (TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, coordinator address).

    This is the no-native-toolchain path to a >2-host mesh env set;
    provision_multi_node_cd is the full-stack (real C++ slice daemon)
    counterpart. Sized by argument, not hardware: the JAX side maps the
    merged plan onto however many host-platform devices exist.
    """

    def __init__(self, n_workers: int = 2, chips_per_worker: int = 4,
                 generation: str = "v5p", slice_id: str = "mesh"):
        import shutil as _shutil
        import tempfile as _tempfile

        from tpu_dra.cdi.handler import CDIHandler as _CDIHandler
        from tpu_dra.cddaemon.dnsnames import stable_name
        from tpu_dra.cdplugin.computedomain import COORDINATOR_PORT
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.device_state import DeviceState as _DS
        from tpu_dra.tpuplugin.sharing import TimeSlicingManager

        self.n_workers = n_workers
        self.chips_per_worker = chips_per_worker
        self.generation = generation
        self.tmp = _tempfile.mkdtemp(prefix="tpu-dra-meshslice-")
        self._rmtree = _shutil.rmtree
        self._claim_seq = 0
        self._prepared = []  # (worker, uid) for close-time unprepare
        peers = ",".join(stable_name(i) for i in range(n_workers))
        self._identity = [{
            "TPU_WORKER_ID": str(w),
            "TPU_WORKER_HOSTNAMES": peers,
            "TPU_PROCESS_COUNT": str(n_workers),
            "TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{COORDINATOR_PORT}",
        } for w in range(n_workers)]
        self.states = []
        self.backends = []
        try:
            for w in range(n_workers):
                backend = FakeBackend(default_fake_chips(
                    chips_per_worker, generation, slice_id=slice_id,
                    worker_index=w, total_workers=n_workers))
                wdir = os.path.join(self.tmp, f"w{w}")
                state = _DS(
                    backend=backend,
                    cdi=_CDIHandler(os.path.join(wdir, "cdi")),
                    checkpoints=CheckpointManager(os.path.join(wdir, "p")),
                    driver_name=apitypes.TPU_DRIVER_NAME,
                    node_name=f"mesh-{w}",
                    ts_manager=TimeSlicingManager(backend))
                self.backends.append(backend)
                self.states.append(state)
        except BaseException:
            self.close()
            raise

    def prepare_claim(self, worker: int, chip_indices=None,
                      devices=None) -> Dict[str, str]:
        """Prepare one allocated claim on `worker` (all its chips by
        default; `devices` overrides with explicit device names) and
        return the claim's CDI-spec env merged with the worker's
        identity vars — exactly what that worker's workload container
        would see."""
        state = self.states[worker]
        if devices is None:
            indices = (chip_indices if chip_indices is not None
                       else [c.index for c in self.backends[worker].chips()])
            devices = [f"chip-{i}" for i in indices]
        uid = f"mesh-claim-{worker}-{self._claim_seq}"
        self._claim_seq += 1
        claim = {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
            "metadata": {"name": uid, "namespace": "default", "uid": uid},
            "spec": {"devices": {"requests": [{"name": "tpu"}]}},
            "status": {"allocation": {"devices": {"results": [
                {"request": "tpu", "driver": apitypes.TPU_DRIVER_NAME,
                 "pool": f"mesh-{worker}", "device": d}
                for d in devices], "config": []}}},
        }
        result = state.prepare_batch([claim])[uid]
        if result.error:
            raise RuntimeError(
                f"mesh harness prepare failed on worker {worker}: "
                f"{result.error}")
        self._prepared.append((worker, uid))
        env = read_claim_env(state._cdi, uid)
        env.update(self._identity[worker])
        return env

    def worker_envs(self):
        """One all-chips claim per worker; the env list a multi-process
        mesh build consumes (meshexport.plan_from_worker_envs)."""
        return [self.prepare_claim(w) for w in range(self.n_workers)]

    def close(self) -> None:
        for worker, uid in self._prepared:
            try:
                self.states[worker].unprepare_batch([uid])
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[test-harness teardown is best-effort; rmtree below removes the residue]
                pass
        self._prepared.clear()
        for state in self.states:
            try:
                state.close()
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[test-harness teardown is best-effort; rmtree below removes the residue]
                pass
        self._rmtree(self.tmp, ignore_errors=True)


def make_sched_pod(client, name: str, namespace: str = "default",
                   template: str = "tmpl"):
    """A pod claiming devices via `template` (the churn fixture's pod
    shape; multi-chip templates are the ``tmpl<n>`` variants that
    seed_sched_inventory's claim_counts stamps)."""
    from tpu_dra.k8s.resources import PODS

    return client.create(PODS, {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"containers": [{"name": "c", "image": "x"}],
                 "resourceClaims": [
                     {"name": "t", "resourceClaimTemplateName": template}]},
    }, namespace=namespace)
