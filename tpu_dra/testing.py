"""Shared multi-node ComputeDomain harness for tests and bench.

One "node" = a CD kubelet plugin (ComputeDomainManager + DeviceState +
CDDriver) plus, once the node is labeled, a DaemonRunner wrapping the real
C++ slice daemon. Used by tests/test_cd_integration.py and bench.py so the
wiring lives in exactly one place.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from tpu_dra.api import types as apitypes
from tpu_dra.cddaemon.main import DaemonRunner, flags as daemon_flags
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.cdplugin.computedomain import ComputeDomainManager
from tpu_dra.cdplugin.device_state import DeviceState
from tpu_dra.cdplugin.driver import CDDriver
from tpu_dra.k8s import NODES
from tpu_dra.tpuplugin.checkpoint import CheckpointManager

CD_CDI_VENDOR = "k8s.compute-domain.tpu.dev"

DAEMON_BIN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "build", "tpu-slice-daemon")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FakeNode:
    """One 'node': a CD kubelet plugin plus (once labeled) a cd daemon."""

    def __init__(self, cluster, name: str, tmp_path, *,
                 slice_id: str = "slice-A", retry_timeout: float = 20.0,
                 daemon_bin: str = DAEMON_BIN):
        self.cluster = cluster
        self.name = name
        self.tmp = tmp_path / name if hasattr(tmp_path, "__truediv__") \
            else _PathShim(os.path.join(str(tmp_path), name))
        self._daemon_bin = daemon_bin
        cluster.create(NODES, {"apiVersion": "v1", "kind": "Node",
                               "metadata": {"name": name}})
        self.cd_manager = ComputeDomainManager(
            cluster, node_name=name,
            driver_plugin_dir=str(self.tmp / "plugin"))
        self.cd_manager.start()
        self.cdi = CDIHandler(str(self.tmp / "cdi"), vendor=CD_CDI_VENDOR)
        self.state = DeviceState(
            cd_manager=self.cd_manager, cdi=self.cdi,
            checkpoints=CheckpointManager(str(self.tmp / "plugin")),
            driver_name=apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
            node_name=name, slice_id=slice_id)
        self.driver = CDDriver(
            state=self.state, client=cluster,
            driver_name=apitypes.COMPUTE_DOMAIN_DRIVER_NAME, node_name=name,
            slice_id=slice_id, plugin_dir=str(self.tmp / "plugin"),
            retry_timeout=retry_timeout)
        self.driver.start()
        self.daemon: Optional[DaemonRunner] = None

    def wait_labeled(self, cd_uid: str, timeout: float = 20.0) -> bool:
        return self.cluster.wait_for(
            lambda: (self.cluster.get(NODES, self.name)["metadata"]
                     .get("labels") or {}).get(
                apitypes.COMPUTE_DOMAIN_LABEL_KEY) == cd_uid,
            timeout=timeout)

    def start_daemon(self, cd) -> None:
        """The DaemonSet-pod analog, started when the node is labeled."""
        ns = daemon_flags().parse([
            "--cd-uid", cd["metadata"]["uid"],
            "--cd-name", cd["metadata"]["name"],
            "--cd-namespace", cd["metadata"]["namespace"],
            "--node-name", self.name, "--pod-ip", "127.0.0.1",
            "--port", str(free_port()),
            "--work-dir", str(self.tmp / "daemon"),
            "--hosts-file", str(self.tmp / "hosts"),
            "--daemon-binary", self._daemon_bin,
        ])
        self.daemon = DaemonRunner(self.cluster, ns)
        self.daemon.start()

    def stop(self) -> None:
        if self.daemon:
            self.daemon.stop()
            self.daemon = None
        self.driver.shutdown()
        self.cd_manager.stop()


class _PathShim:
    """Minimal pathlib-like '/'-join for plain-string tmp dirs (bench)."""

    def __init__(self, path: str):
        self._path = path

    def __truediv__(self, other: str) -> "_PathShim":
        return _PathShim(os.path.join(self._path, other))

    def __str__(self) -> str:
        return self._path
