"""Fault-injection substrate: named sites, armed with schedules.

The reference driver's operational value is surviving partial failure
(NVML event storms, kubelet restarts, API-server flakes), but none of
that is drivable deterministically from tests. This registry gives
production code cheap guard calls at the places failure actually enters
the system — a *site* — and gives chaos tests a way to arm each site
with a *schedule* (every-Nth, probabilistic, one-shot) deciding which
guard invocations fire.

Guard styles, by what the site needs on failure:

- ``check(site, **ctx)``  — raise ``FaultInjected`` (or run the armed
  action with ``ctx``) when the schedule fires; no-op otherwise. For
  sites whose failure mode is an exception (API request, CDI write,
  checkpoint store).
- ``fires(site)``         — plain bool, for sites that model failure as
  control flow (dropping a watch stream) rather than an exception.
- ``pull(site)``          — return the armed payload when the schedule
  fires, else None. For sites that *inject data* (a synthetic chip
  health event) rather than an error.

The disarmed fast path is a single dict emptiness test — cheap enough
to leave on hot paths permanently. All state transitions take a lock;
guards may be hit from many threads (watch loops, workqueues, gRPC
handlers).
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


class FaultInjected(Exception):
    """Raised by a fired ``check`` guard with no custom action armed."""

    def __init__(self, site: str, detail: str = ""):
        super().__init__(f"injected fault at {site}"
                         + (f": {detail}" if detail else ""))
        self.site = site


# ---------------------------------------------------------------------------
# Schedules: when does an armed site fire?
# ---------------------------------------------------------------------------

class Schedule:
    """Decides, per guard invocation, whether the armed fault fires.
    ``__call__`` runs under the registry lock — keep it cheap."""

    def __call__(self) -> bool:
        raise NotImplementedError


class EveryNth(Schedule):
    """Fire on every Nth invocation (the deterministic flake)."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError("n must be >= 1")
        self._n = n
        self._count = 0

    def __call__(self) -> bool:
        self._count += 1
        return self._count % self._n == 0


class Probabilistic(Schedule):
    """Fire with probability p per invocation; seeded rng for replay."""

    def __init__(self, p: float, rng: Optional[random.Random] = None):
        if not 0.0 <= p <= 1.0:
            raise ValueError("p must be in [0, 1]")
        self._p = p
        self._rng = rng or random.Random()

    def __call__(self) -> bool:
        return self._rng.random() < self._p


class OneShot(Schedule):
    """Fire exactly once, optionally skipping the first `after` calls."""

    def __init__(self, after: int = 0):
        self._skip = after
        self._fired = False

    def __call__(self) -> bool:
        if self._fired:
            return False
        if self._skip > 0:
            self._skip -= 1
            return False
        self._fired = True
        return True


class Always(Schedule):
    """Fire on every invocation (hard outage until disarmed)."""

    def __call__(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Site catalog
# ---------------------------------------------------------------------------

# Every injection site production code consults, with the invariant its
# failure threatens (mirrored in SURVEY.md "Failure model & fault sites").
SITES: Dict[str, str] = {
    "k8s.api.request":
        "API request fails transiently (429/500/503, socket error); "
        "threatens: reconcile convergence, ResourceSlice freshness",
    "k8s.watch.drop":
        "watch stream dies mid-flight; threatens: informer cache "
        "staleness if resume loses events",
    "cdi.claim_write":
        "per-claim CDI spec write fails; threatens: orphaned spec files, "
        "claims stuck half-prepared",
    "prepare.batch_fetch":
        "per-claim ResourceClaim fetch in the batch fan-out fails; "
        "threatens: per-claim error isolation (one 404/flake must not "
        "fail the rest of the NodePrepareResources batch)",
    "prepare.batch_apply":
        "per-claim side-effect application in the batch path fails "
        "mid-batch; threatens: group-commit atomicity (survivors must "
        "commit durably, the loser must roll back cleanly)",
    "checkpoint.store":
        "checkpoint store fails; threatens: claim state-machine "
        "durability, prepare idempotency",
    "prepare.rpc_admit":
        "pipelined RPC admission refuses the RPC before a window slot "
        "or ordering gate is registered (the async front-end's "
        "admission seam, SURVEY §21); threatens: per-claim error "
        "surfacing — the RPC must fail with retryable per-claim errors "
        "and leak neither a window slot nor a claim-uid gate a "
        "successor would wait on forever",
    "prepare.journal_append":
        "append-only checkpoint journal append fails (ENOSPC on the "
        "journal while the slot scheme may still work); threatens: "
        "terminal group-commit durability — the caller must unwind "
        "exactly like a failed terminal store",
    "prepare.journal_compact":
        "bounded-lag journal compaction fails (slot store ENOSPC, "
        "swap rename EIO); threatens: recovery replay length and "
        "journal growth — appends must keep landing and lag must "
        "recover once the fault clears",
    "checkpoint.corrupt":
        "slot file torn/corrupted after a store (action scribbles on the "
        "written paths); threatens: recovery after crash",
    "sched.watch_event":
        "scheduler-side watch event mishandled before the allocation "
        "index/pending set applies it (the handler drops it and marks "
        "the index dirty); threatens: allocated-device index staleness "
        "— the guarded full-resync fallback must converge",
    "sched.index_apply":
        "incremental allocated-device index apply/remove fails; "
        "threatens: index vs cluster-truth divergence, device "
        "double-allocation if an allocation proceeded on a dirty index",
    "sched.shard_apply":
        "per-shard allocation-index mutation fails after routing (the "
        "shard is left unchanged and marked dirty); threatens: per-shard "
        "index==truth divergence — the shard-scoped resync must recover "
        "without blocking scans on sibling shards",
    "sched.snapshot_commit":
        "optimistic snapshot commit refused (models the shard moving "
        "underneath a lock-free candidate scan); threatens: device "
        "double-allocation if a worker committed a stale pick anyway — "
        "the conflict must surface as a bounded re-scan/requeue, never "
        "a partial reservation",
    "cddaemon.spawn":
        "slice-daemon child fails to spawn; threatens: readiness "
        "mirroring, CD convergence",
    "mesh.build":
        "allocation -> mesh plan construction fails (torn topology env, "
        "stale coordinate export, refused rank mapping); threatens: the "
        "data-plane handoff — a workload must see a loud refusal and "
        "retry against fresh claim state, never a silently mis-ordered "
        "mesh whose collectives ride long ICI paths",
    "workload.launch":
        "workload launch on a built mesh fails (admission refusal, "
        "compile/dispatch error at the data-plane seam); threatens: "
        "per-workload bench attribution — one failing launch must "
        "isolate to its own workload record, not blank sibling "
        "workloads or unwind the mesh",
    "health.chip_event":
        "synthetic chip health event (payload-injecting site); "
        "threatens: ResourceSlice vs healthy-chip consistency",
    "health.flap":
        "quarantine-ladder graduation fails to persist (journal append "
        "ENOSPC while a flapping chip crosses the threshold); threatens: "
        "quarantine durability — the chip must stay transient-unhealthy "
        "and re-graduate on the next flap, never half-quarantine or "
        "crash the health callback",
    "sched.evict":
        "eviction of a claim whose allocated chips died fails mid-flight "
        "(deallocation write refused, pod unbind conflict); threatens: "
        "failure-domain convergence — the evict scan must retry with "
        "backoff until every claim ends Allocated-on-live-chips or "
        "Pending-with-reason, never a claim pinned to a dead chip",
    "cd.member_loss":
        "ComputeDomain member-loss handling fails (Degraded status write "
        "conflict, daemon peer-config rewrite error); threatens: a CD "
        "stuck Ready with a dead member, or a daemon crash-looping on "
        "dead peers instead of backing off",
    "trace.emit":
        "span emission into the flight recorder fails (a real "
        "exporter's queue-full/serialization error); threatens: the "
        "span pipeline's degradation contract — the span must drop "
        "counted (trace marked incomplete), the traced operation must "
        "never see the failure, and quiesce invariants must still hold",
    "sched.lease_renew":
        "the leader's lease-renew write fails (apiserver blip, CAS "
        "conflict against a racing takeover); threatens: split brain — "
        "a leader that cannot renew past the lease duration must step "
        "down, and its late claim-status commits must be refused by "
        "the fencing generation, never land next to the new leader's",
    "sched.takeover_resync":
        "the standby's takeover index rebuild fails mid-promotion "
        "(listing refused, shard resync raced); threatens: the new "
        "leader allocating against a stale AllocationIndex — the "
        "takeover must re-drive the guarded resync before commits, "
        "never double-allocate a device the old leader placed",
    "prepare.drain":
        "the hot-restart drain window fails (in-flight RPC wedged past "
        "the bound, drain wait interrupted); threatens: the "
        "zero-failed-RPC restart contract — shutdown must dump "
        "flight-recorder evidence and proceed, leaving clients to mask "
        "the gap by reconnect-retry against the restarted plugin",
    "prepare.reconnect":
        "a client's reconnect dial fails while masking the plugin "
        "restart's socket gap (socket not yet re-listening, transient "
        "ECONNREFUSED); threatens: RPC loss across the restart — the "
        "masking retry must back off and redial within its bound, "
        "never surface the gap to the caller as a failed RPC",
    "sched.watch_shard_dispatch":
        "a partitioned informer's shard delta FIFO refuses an offered "
        "handler dispatch (models the bounded queue at capacity under "
        "fan-out burst); threatens: allocation-index staleness for that "
        "shard — the shed delta must surface through the overflow hook "
        "so the shard is marked dirty and resynced, never silently "
        "skipped while try_commit keeps allocating against it",
    "sched.informer_shard_relist":
        "the scheduler's shard-overflow recovery fails before the "
        "shard-scoped dirty+resync lands (index lock contention, resync "
        "enqueue refused); threatens: a shard that lost deltas staying "
        "clean-looking — the degradation must fall back to marking the "
        "whole index dirty so the guarded full resync converges it",
}

# Declared degradations (drflow R15, SURVEY §20): sites whose injected
# failure has ONE sanctioned degrade path. A broad except handler
# whose try body guards one of these sites must route to the named
# helper (call-chain tail contains the name) or re-raise — an injected
# fault that only gets logged leaves the degrade path chaos thinks is
# covered untested. Sites absent here only owe the generic non-swallow
# discipline.
DEGRADATIONS: Dict[str, str] = {
    # A failed shard apply MUST dirty the shard so the guarded
    # full-resync fallback converges it (scheduler._checked_shard).
    # (cd.member_loss deliberately has NO entry: the controller
    # degrades the domain but the daemon's sanctioned reaction is a
    # re-offered retry — two valid paths, no single declared one.)
    "sched.shard_apply": "mark_dirty",
    # A renew that keeps failing past the lease duration has ONE legal
    # exit: step down (fencing refuses the late writes either way —
    # stepping down just stops throwing work at a lost lease).
    "sched.lease_renew": "step_down",
    # A faulted takeover rebuild re-drives the guarded resync through
    # the queue (scheduler.request_resync) rather than promoting onto
    # a dirty index.
    "sched.takeover_resync": "request_resync",
    # A drain that cannot complete dumps the flight recorder (the
    # wedged in-flight RPC is named by its open span) and shutdown
    # proceeds; clients mask the gap by reconnect-retry.
    "prepare.drain": "dump_flight_recorder",
    # A failed reconnect dial stays on the bounded backoff-redial path
    # (RetryingFramedClient._reconnect_backoff) — masking, not failing.
    "prepare.reconnect": "backoff",
    # A refused shard dispatch has ONE sanctioned exit: shed the delta
    # and report the shard through the overflow hook
    # (ShardDispatcher._shard_overflow) so the consumer resyncs it.
    "sched.watch_shard_dispatch": "shard_overflow",
    # When even the shard-scoped recovery faults, fall back to dirtying
    # the whole index (scheduler._mark_dirty) — over-resync is safe,
    # a clean-looking shard that lost deltas is not.
    "sched.informer_shard_relist": "mark_dirty",
}


# Observer called (outside the registry lock) with the site name every
# time an armed site fires — the flight recorder (infra/trace.py)
# installs itself here so fault firings land in the evidence ring next
# to the spans they perturbed. A hook rather than an import keeps this
# module dependency-free.
_fire_observer: Optional[Callable[[str], None]] = None


def set_fire_observer(observer: Optional[Callable[[str], None]]) -> None:
    global _fire_observer
    _fire_observer = observer


@dataclass
class _Armed:
    schedule: Schedule
    action: Optional[Callable[..., Any]] = None
    payload: Any = None
    fired: int = 0
    calls: int = 0
    detail: str = ""


class FaultRegistry:
    """Registry of injection sites; one global instance (``FAULTS``) is
    consulted by production guards, tests arm/disarm on it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, _Armed] = {}
        self._sites = dict(SITES)

    # -- site catalog -------------------------------------------------------

    def register_site(self, site: str, description: str) -> None:
        """Extension point for out-of-tree sites (tests, plugins)."""
        with self._lock:
            self._sites.setdefault(site, description)

    def sites(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._sites)

    # -- arming -------------------------------------------------------------

    def arm(self, site: str, schedule: Schedule, *,
            action: Optional[Callable[..., Any]] = None,
            payload: Any = None, detail: str = "") -> None:
        """Arm `site` with `schedule`. When a ``check`` guard fires:
        `action(**ctx)` runs if given (it decides whether/what to raise),
        else ``FaultInjected`` is raised. `payload` is what ``pull``
        returns on fire. Unknown site names are rejected — a typo here
        would silently chaos-test nothing."""
        with self._lock:
            if site not in self._sites:
                raise KeyError(f"unknown fault site {site!r} "
                               f"(known: {sorted(self._sites)})")
            self._armed[site] = _Armed(schedule=schedule, action=action,
                                       payload=payload, detail=detail)

    def disarm(self, site: str) -> None:
        with self._lock:
            self._armed.pop(site, None)

    def reset(self) -> None:
        """Disarm everything (chaos quiesce / test teardown)."""
        with self._lock:
            self._armed.clear()

    @contextmanager
    def armed(self, site: str, schedule: Schedule, *,
              action: Optional[Callable[..., Any]] = None,
              payload: Any = None, detail: str = ""):
        """Scoped arm for tests: disarms on exit no matter what."""
        self.arm(site, schedule, action=action, payload=payload,
                 detail=detail)
        try:
            yield self
        finally:
            self.disarm(site)

    # -- guards (production call sites) -------------------------------------

    def _fire(self, site: str) -> Optional[_Armed]:
        # Disarmed fast path: a plain dict emptiness/membership test,
        # no lock (dict reads are atomic under the GIL; a racing arm()
        # is observed on the next guard hit, which is all chaos needs).
        if site not in self._armed:  # dralint: ignore[R10] — deliberate lock-free fast path: GIL-atomic membership test, a racing arm() lands on the next guard hit
            return None
        with self._lock:
            armed = self._armed.get(site)
            if armed is None:
                return None
            armed.calls += 1
            if not armed.schedule():
                return None
            armed.fired += 1
        # Observer outside the lock: the flight recorder's ring append
        # is cheap, but an observer must never extend the registry
        # lock's hold window (guards run on every hot path).
        observer = _fire_observer
        if observer is not None:
            observer(site)
        return armed

    def fires(self, site: str) -> bool:
        """Control-flow guard: True when the armed schedule fires."""
        return self._fire(site) is not None

    def check(self, site: str, **ctx) -> None:
        """Exception guard: raise FaultInjected (or run the armed action
        with `ctx`) when the schedule fires; no-op otherwise."""
        armed = self._fire(site)
        if armed is None:
            return
        if armed.action is not None:
            armed.action(**ctx)
            return
        raise FaultInjected(site, armed.detail)

    def pull(self, site: str) -> Any:
        """Payload guard: the armed payload when the schedule fires
        (a callable payload is invoked to mint the value), else None."""
        armed = self._fire(site)
        if armed is None:
            return None
        payload = armed.payload
        return payload() if callable(payload) else payload

    # -- introspection ------------------------------------------------------

    def fired(self, site: str) -> int:
        with self._lock:
            armed = self._armed.get(site)
            return armed.fired if armed else 0

    def counts(self) -> Dict[str, int]:
        """site -> times fired, for armed sites (chaos reports)."""
        with self._lock:
            return {s: a.fired for s, a in self._armed.items()}

    def take_counts(self) -> Dict[str, int]:
        """counts(), zeroing the fired counters — so a chaos run that
        re-arms sites mid-walk can accumulate without double counting."""
        with self._lock:
            out = {s: a.fired for s, a in self._armed.items()}
            for a in self._armed.values():
                a.fired = 0
            return out


# The process-global registry every production guard consults.
FAULTS = FaultRegistry()
