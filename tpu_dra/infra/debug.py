"""Debug signal handlers.

Reference: internal/common/util.go:30-73 — SIGUSR2 dumps all goroutine
stacks to /tmp/goroutine-stacks.dump in every binary (verified by
tests/bats/test_basics.bats:89-100). Python analog: dump every thread's
stack to /tmp/thread-stacks.dump.
"""

from __future__ import annotations

import faulthandler
import signal
import sys
import threading
import traceback

STACK_DUMP_PATH = "/tmp/thread-stacks.dump"


def dump_stacks(path: str = STACK_DUMP_PATH) -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    with open(path, "w") as f:
        for ident, frame in frames.items():
            f.write(f"--- thread {names.get(ident, '?')} ({ident}) ---\n")
            traceback.print_stack(frame, file=f)
            f.write("\n")
    return path


def start_debug_signal_handlers(path: str = STACK_DUMP_PATH) -> None:
    """Install SIGUSR2 -> stack dump. Also arms faulthandler for hard
    crashes. Only callable from the main thread (signal API restriction)."""
    faulthandler.enable()

    def _handler(signum, frame):
        try:
            dump_stacks(path)
        except Exception as e:  # noqa: BLE001
            # Signal-handler context: logging machinery may deadlock;
            # a raw stderr line is async-signal-tolerable and beats a
            # dump that silently never happened.
            sys.stderr.write(f"stack dump to {path} failed: {e}\n")

    signal.signal(signal.SIGUSR2, _handler)
