"""Versioned feature gates.

Reference: pkg/featuregates/featuregates.go:31-156 — k8s component-base style
versioned feature gates, threaded into templates as a ``FEATURE_GATES`` env
var. We keep the same lifecycle model (Alpha/Beta/GA + lockToDefault) and the
same spelling of the gate-string syntax (``Name=true,Other=false``) so Helm
values and env plumbing round-trip identically.

TPU gate mapping (SURVEY.md §2.8):
- TimeSlicingSettings            -> TimeSlicingSettings (chip time-slice config)
- MPSSupport                     -> MultiprocessSupport (libtpu multi-process sharing)
- IMEXDaemonsWithDNSNames        -> SliceDaemonsWithDNSNames (stable per-clique DNS names)
- PassthroughSupport             -> PassthroughSupport (/dev/vfio accel passthrough)
- NVMLDeviceHealthCheck          -> TPUDeviceHealthCheck (accel driver health events)
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Tuple

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass
class FeatureSpec:
    """One gate's lifecycle at a particular driver version."""
    default: bool
    lock_to_default: bool = False
    pre_release: str = ALPHA


@dataclass
class VersionedSpecs:
    """Version-ordered specs; the active spec is the newest one whose
    introduced-version is <= the compiled driver version (we only model the
    newest, matching how the reference resolves gates at startup)."""
    specs: Tuple[Tuple[str, FeatureSpec], ...] = field(default_factory=tuple)

    def current(self) -> FeatureSpec:
        return self.specs[-1][1]


# Gate names
TimeSlicingSettings = "TimeSlicingSettings"
MultiprocessSupport = "MultiprocessSupport"
SliceDaemonsWithDNSNames = "SliceDaemonsWithDNSNames"
PassthroughSupport = "PassthroughSupport"
TPUDeviceHealthCheck = "TPUDeviceHealthCheck"
# TPU-native (no reference analog): ICI-topology-scored device picks +
# slice-aligned ComputeDomain placement (tpu_dra.topology).
TopologyAwareScheduling = "TopologyAwareScheduling"

_DEFAULT_FEATURES: Dict[str, VersionedSpecs] = {
    TimeSlicingSettings: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=False, pre_release=ALPHA)),
    )),
    MultiprocessSupport: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=False, pre_release=ALPHA)),
    )),
    # Default-on, like IMEXDaemonsWithDNSNames (featuregates.go: default true).
    SliceDaemonsWithDNSNames: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=True, pre_release=BETA)),
    )),
    PassthroughSupport: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=False, pre_release=ALPHA)),
    )),
    TPUDeviceHealthCheck: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=True, pre_release=BETA)),
    )),
    TopologyAwareScheduling: VersionedSpecs((
        ("0.1.0", FeatureSpec(default=False, pre_release=ALPHA)),
    )),
}


class FeatureGate:
    """Mutable-until-frozen feature gate registry.

    Mirrors the semantics the reference gets from k8s component-base:
    unknown gates error, locked gates refuse overrides, and the parsed
    state is process-global (gates are consulted from deep inside config
    Normalize/Validate paths).
    """

    def __init__(self, features: Dict[str, VersionedSpecs] | None = None):
        self._lock = threading.Lock()
        self._features = dict(features if features is not None else _DEFAULT_FEATURES)
        self._overrides: Dict[str, bool] = {}

    def known(self) -> Iterable[str]:
        # Under the lock: sorted() iterates the dict, and a concurrent
        # add() mid-iteration raises (draracer R10 caught this).
        with self._lock:
            return sorted(self._features)

    def add(self, name: str, spec: VersionedSpecs) -> None:
        with self._lock:
            if name in self._features:
                raise ValueError(f"feature gate {name} already registered")
            self._features[name] = spec

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name not in self._features:
                raise KeyError(f"unknown feature gate: {name}")
            if name in self._overrides:
                return self._overrides[name]
            return self._features[name].current().default

    def set_from_map(self, values: Dict[str, bool]) -> None:
        """Validate the whole map, then commit atomically (half-applied gate
        sets must never be observable, matching component-base semantics).
        All rejection paths raise ValueError."""
        with self._lock:
            staged: Dict[str, bool] = {}
            for name, val in values.items():
                if name not in self._features:
                    raise ValueError(f"unknown feature gate: {name}")
                spec = self._features[name].current()
                if spec.lock_to_default and val != spec.default:
                    raise ValueError(
                        f"cannot set feature gate {name} to {val}: locked to {spec.default}")
                staged[name] = val
            self._overrides.update(staged)

    def set_from_string(self, s: str) -> None:
        """Parse ``Name=true,Other=false`` (the FEATURE_GATES env format)."""
        values: Dict[str, bool] = {}
        for part in filter(None, (p.strip() for p in s.split(","))):
            if "=" not in part:
                raise ValueError(f"missing '=' in feature gate assignment {part!r}")
            name, _, raw = part.partition("=")
            raw = raw.strip().lower()
            if raw not in ("true", "false"):
                raise ValueError(f"invalid boolean {raw!r} for feature gate {name!r}")
            values[name.strip()] = raw == "true"
        self.set_from_map(values)

    def snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return {n: self._overrides.get(n, vs.current().default)
                    for n, vs in self._features.items()}

    def as_string(self) -> str:
        return ",".join(f"{n}={'true' if v else 'false'}"
                        for n, v in sorted(self.snapshot().items()))

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()

    def overrides_snapshot(self) -> Dict[str, bool]:
        """The explicit overrides only (unlike snapshot(), which folds in
        defaults) — the value restore_overrides() round-trips, for code
        that must temporarily flip gates without wiping what the process
        set before it."""
        with self._lock:
            return dict(self._overrides)

    def restore_overrides(self, overrides: Dict[str, bool]) -> None:
        with self._lock:
            self._overrides = dict(overrides)


# Process-global gate registry, like the reference's package-level Features.
Features = FeatureGate()


def enabled(name: str) -> bool:
    return Features.enabled(name)
