"""Kubernetes-style resource quantity parsing.

The reference leans on apimachinery's ``resource.Quantity`` for MPS
pinned-device-memory limits (api/nvidia.com/resource/v1beta1/sharing.go).
We implement the subset the driver needs: binary (Ki..Ei) and decimal
(k..E, m) suffixes, canonical round-trip, and byte conversion.
"""

from __future__ import annotations

from fractions import Fraction

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3,
           "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9,
            "T": 10**12, "P": 10**15, "E": 10**18, "m": Fraction(1, 1000)}
_SUFFIXES = tuple(sorted(list(_BINARY) + list(_DECIMAL), key=len, reverse=True))


class Quantity:
    """Immutable parsed quantity; compares by value."""

    __slots__ = ("_value", "_text")

    def __init__(self, text: str):
        if isinstance(text, (int, float)):
            text = str(text)
        s = text.strip()
        if not s:
            raise ValueError("empty quantity")
        suffix = ""
        for cand in _SUFFIXES:
            if s.endswith(cand):
                suffix = cand
                s = s[: -len(cand)]
                break
        try:
            num = Fraction(s)
        except (ValueError, ZeroDivisionError) as e:
            raise ValueError(f"invalid quantity {text!r}") from e
        mult = _BINARY.get(suffix) or _DECIMAL.get(suffix) or 1
        self._value = num * mult
        self._text = text.strip()

    @property
    def value(self) -> int:
        """Integer value, rounding up (matches apimachinery Value())."""
        v = self._value
        return int(v) if v.denominator == 1 else int(v) + (1 if v > 0 else 0)

    def __eq__(self, other) -> bool:
        return isinstance(other, Quantity) and self._value == other._value

    def __lt__(self, other: "Quantity") -> bool:
        return self._value < other._value

    def __hash__(self) -> int:
        return hash(self._value)

    def __str__(self) -> str:
        return self._text

    def __repr__(self) -> str:
        return f"Quantity({self._text!r})"


def parse_quantity(text: str) -> Quantity:
    return Quantity(text)
