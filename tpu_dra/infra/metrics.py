"""Minimal Prometheus-compatible metrics registry + HTTP exposition.

Reference: cmd/compute-domain-controller/main.go:243-290 — an HTTP endpoint
serving Prometheus metrics (client-go/workqueue/restclient collectors via
legacyregistry) and pprof profiles behind --http-endpoint/--pprof-path.
Python analog: counters/gauges/histograms with label support, text
exposition format, and a background http.server that also serves the
thread-stack dump at the pprof path.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from tpu_dra.infra import debug


def _escape_label_value(value: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash,
    double-quote and newline must be escaped or a hostile/accidental
    value ('say "hi"\\n') tears the scrape line."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (quotes are legal)."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


class _Metric:
    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[Dict[str, str]]):
        return tuple(sorted((labels or {}).items()))

    def value(self, labels: Optional[Dict[str, str]] = None,
              default: float = 0.0) -> float:
        """Current scalar for one label set — the programmatic read seam
        tests and the bench use instead of scraping the text exposition.

        Empty-state contract: a label set never touched returns
        `default` (0.0) — identical to a counter that exists but never
        incremented, which is what PromQL's absent-as-zero arithmetic
        assumes. Callers that must distinguish "never touched" from
        "zero" pass a sentinel default or check ``labelsets()``."""
        with self._lock:
            return self._values.get(self._key(labels), default)

    def labelsets(self) -> List[Dict[str, str]]:
        """Label sets that have actually been touched — the explicit
        never-touched-vs-zero discriminator ``value()`` cannot be."""
        with self._lock:
            return [dict(k) for k in sorted(self._values)]

    def expose(self) -> List[str]:
        # Label sets render stably sorted (the _key tuples are
        # themselves label-name-sorted), so consecutive scrapes of the
        # same state are byte-identical and scrape diffs stay readable.
        with self._lock:
            lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                     f"# TYPE {self.name} {self.kind}"]
            for key, val in sorted(self._values.items()):
                if key:
                    lbl = ",".join(
                        f'{k}="{_escape_label_value(v)}"' for k, v in key)
                    lines.append(f"{self.name}{{{lbl}}} {val}")
                else:
                    lines.append(f"{self.name} {val}")
            return lines


class Counter(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "counter")

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount


class Gauge(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "gauge")

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(labels)] = value


class Histogram(_Metric):
    """Fixed-bucket histogram; exposes _bucket/_sum/_count series."""

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    def __init__(self, name: str, help_text: str = "", buckets=None):
        super().__init__(name, help_text, "histogram")
        self._buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self._buckets) + 1)
        self._sum = 0.0
        self._n = 0

    def observe(self, value: float):
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self._buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        """Observations so far (the _count series, programmatically)."""
        with self._lock:
            return self._n

    @property
    def total(self) -> float:
        """Sum of observed values (the _sum series, programmatically)."""
        with self._lock:
            return self._sum

    def expose(self) -> List[str]:
        with self._lock:
            lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                     f"# TYPE {self.name} histogram"]
            cum = 0
            for b, c in zip(self._buckets, self._counts):
                cum += c
                lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {self._n}')
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._n}")
            return lines

    @property
    def empty(self) -> bool:
        """True while nothing has been observed — the explicit check
        for callers that must not mistake the empty-state percentile
        default for a measured zero."""
        with self._lock:
            return self._n == 0

    def percentile(self, q: float, default: float = 0.0) -> float:
        """Approximate percentile from bucket upper bounds (for
        bench/report).

        Empty-state contract: with zero observations there is no
        distribution to query, so `default` (0.0) is returned — pinned
        by test, documented here, and distinguishable via ``empty`` /
        ``count`` rather than silently ambiguous. Values above the
        largest finite bucket report +Inf (the bucket that holds them)."""
        with self._lock:
            if self._n == 0:
                return default
            target = q * self._n
            cum = 0
            for b, c in zip(self._buckets, self._counts):
                cum += c
                if cum >= target:
                    return b
            return float("inf")

    def bucket_counts(self) -> Tuple[int, ...]:
        """Raw per-bucket counts snapshot (finite buckets + overflow) —
        the baseline handle for ``percentile_since``."""
        with self._lock:
            return tuple(self._counts)

    def percentile_since(self, baseline: Tuple[int, ...], q: float,
                         default: float = 0.0) -> float:
        """``percentile`` over only the observations made AFTER
        `baseline` (a ``bucket_counts()`` snapshot) — the phase-scoped
        read a bench window needs when the histogram already carries a
        process lifetime of observations. Same contracts as
        ``percentile``: `default` when the window is empty, +Inf above
        the largest finite bucket."""
        with self._lock:
            deltas = [c - b for c, b in zip(self._counts, baseline)]
            n = sum(deltas)
            if n == 0:
                return default
            target = q * n
            cum = 0
            for b, c in zip(self._buckets, deltas):
                cum += c
                if cum >= target:
                    return b
            return float("inf")


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self.register(Counter(name, help_text))  # type: ignore[return-value]

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self.register(Gauge(name, help_text))  # type: ignore[return-value]

    def histogram(self, name: str, help_text: str = "", buckets=None) -> Histogram:
        return self.register(Histogram(name, help_text, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        with self._lock:
            out: List[str] = []
            for m in self._metrics:
                out.extend(m.expose())
            return "\n".join(out) + "\n"


DefaultRegistry = Registry()

# ---------------------------------------------------------------------------
# Metric catalog (dralint R5)
# ---------------------------------------------------------------------------
# Every metric the project registers, wherever its DefaultRegistry.
# counter/gauge/histogram call lives — the single place dashboards, the
# perf gates (hack/perf.sh) and SURVEY reference. dralint enforces both
# directions: a registration whose name is missing here fails lint, and
# a cataloged name nobody registers is an orphan. Names must match
# ``tpu_dra_[a-z0-9_]+``.
METRICS_CATALOG: Dict[str, str] = {
    # tpuplugin/driver.py — kubelet-facing prepare pipeline
    "tpu_dra_claim_prepare_seconds": "tpuplugin/driver.py",
    "tpu_dra_prepare_batch_size": "tpuplugin/driver.py",
    "tpu_dra_prepare_wire_decode_seconds": "tpuplugin/driver.py",
    "tpu_dra_prepare_wire_queue_seconds": "tpuplugin/driver.py",
    "tpu_dra_prepare_wire_encode_seconds": "tpuplugin/driver.py",
    # kubeletplugin/pipeline.py — pipelined RPC admission
    "tpu_dra_prepare_inflight_rpcs": "kubeletplugin/pipeline.py",
    # kubeletplugin/aio_server.py — async RPC front-end (SURVEY §21):
    # event-loop scheduling-lag histogram (blocking work leaked onto
    # the loop shows here first) and the front-end-wide in-flight RPC
    # gauge the sustained-load bench watches
    "tpu_dra_rpc_loop_lag_seconds": "kubeletplugin/aio_server.py",
    "tpu_dra_rpc_sustained_inflight": "kubeletplugin/aio_server.py",
    # tpuplugin/health.py + device_state.py — failure-domain recovery
    # (SURVEY §18): the wedged-monitor tripwire and the chip-quarantine
    # ladder's exclusion count
    "tpu_dra_health_monitor_wedged": "tpuplugin/health.py",
    "tpu_dra_quarantined_chips": "tpuplugin/device_state.py",
    # tpuplugin/checkpoint.py — append-only journal + group commit
    "tpu_dra_journal_appends_total": "tpuplugin/checkpoint.py",
    "tpu_dra_journal_group_syncs_total": "tpuplugin/checkpoint.py",
    "tpu_dra_journal_compactions_total": "tpuplugin/checkpoint.py",
    "tpu_dra_journal_lag_records": "tpuplugin/checkpoint.py",
    "tpu_dra_journal_window_holds_total": "tpuplugin/checkpoint.py",
    "tpu_dra_journal_rotations_total": "tpuplugin/checkpoint.py",
    # cdplugin/driver.py — ComputeDomain channel prepare
    "tpu_dra_cd_claim_prepare_seconds": "cdplugin/driver.py",
    # cdcontroller/controller.py — CD reconcile loop + failure-domain
    # transitions (Ready -> Degraded on member loss, SURVEY §18)
    "tpu_dra_cd_reconciles_total": "cdcontroller/controller.py",
    "tpu_dra_cd_teardowns_total": "cdcontroller/controller.py",
    "tpu_dra_cd_degraded_total": "cdcontroller/controller.py",
    # k8s/informer.py — watch-stream health: relists forced by stream
    # failures (drflow R15: the silent relist loop made loud), and
    # partitioned-dispatch drops (shard FIFO bound or injected fault;
    # the consumer's overflow hook owns the dirty+resync recovery)
    "tpu_dra_informer_relists_total": "k8s/informer.py",
    "tpu_dra_informer_shard_overflows_total": "k8s/informer.py",
    # infra/metrics.py — shared control-plane instruments (below)
    "tpu_dra_cel_cache_hits": "infra/metrics.py",
    "tpu_dra_cel_cache_misses": "infra/metrics.py",
    "tpu_dra_cel_compiles": "infra/metrics.py",
    "tpu_dra_sched_full_relists": "infra/metrics.py",
    "tpu_dra_sched_watch_events": "infra/metrics.py",
    "tpu_dra_sched_pods_bound": "infra/metrics.py",
    "tpu_dra_sched_claims_gced": "infra/metrics.py",
    # infra/metrics.py — parallel scheduler core (SURVEY §15): worker
    # pool size, optimistic snapshot-commit conflicts, shard-scoped
    # resyncs, and the shared workqueue depth/busy gauges
    "tpu_dra_sched_workers": "infra/metrics.py",
    "tpu_dra_sched_snapshot_conflicts_total": "infra/metrics.py",
    "tpu_dra_sched_shard_resyncs_total": "infra/metrics.py",
    "tpu_dra_sched_evictions_total": "infra/metrics.py",
    # infra/metrics.py — HA control plane (SURVEY §22): leader-lease
    # state + transition volume; kubeletplugin — the hot-restart drain
    # window and the client-side reconnect masking counter the
    # zero-failed-RPC restart gate reads
    "tpu_dra_sched_leader": "infra/metrics.py",
    "tpu_dra_sched_lease_transitions_total": "infra/metrics.py",
    "tpu_dra_rpc_drain_seconds": "kubeletplugin/pipeline.py",
    "tpu_dra_rpc_reconnects_total": "kubeletplugin/server.py",
    "tpu_dra_workqueue_depth": "infra/metrics.py",
    "tpu_dra_workqueue_busy_workers": "infra/metrics.py",
    "tpu_dra_topo_allocations": "infra/metrics.py",
    "tpu_dra_topo_score_seconds": "infra/metrics.py",
    "tpu_dra_topo_free_cuboid_chips": "infra/metrics.py",
    # infra/metrics.py — allocation -> mesh data-plane handoff (SURVEY
    # §17): plan builds by outcome (ok/fragmented/refused), measured
    # psum bandwidth on allocated meshes, and the contiguous-vs-
    # fragmented placement A/B delta the perf tier gates on
    "tpu_dra_mesh_builds_total": "infra/metrics.py",
    "tpu_dra_psum_bandwidth_gbps": "infra/metrics.py",
    "tpu_dra_psum_ab_delta_gbps": "infra/metrics.py",
    # infra/metrics.py — drmc model-checker exploration stats (consumed
    # by hack/drmc.sh gates; labeled by scenario)
    "tpu_dra_drmc_schedules_total": "infra/metrics.py",
    "tpu_dra_drmc_crashpoints_total": "infra/metrics.py",
    # analysis/core.py — dralint/draracer lint-tier observability:
    # finding volume and per-file result-cache effectiveness (stat tier
    # + the content-hash fallback tier), trended by CI
    "tpu_dra_lint_findings_total": "analysis/core.py",
    "tpu_dra_lint_cache_hits_total": "analysis/core.py",
    # infra/trace.py — the claim-tracing span layer + flight recorder
    # (SURVEY §19): span lifecycle volume (started/completed by status/
    # dropped at the trace.emit seam), the evidence ring's occupancy,
    # and dumps written by trigger (wedged|chaos-violation|sigusr1)
    "tpu_dra_trace_spans_started_total": "infra/trace.py",
    "tpu_dra_trace_spans_completed_total": "infra/trace.py",
    "tpu_dra_trace_spans_dropped_total": "infra/trace.py",
    "tpu_dra_flightrecorder_ring_occupancy": "infra/trace.py",
    "tpu_dra_flightrecorder_dumps_total": "infra/trace.py",
}


class MetricsServer:
    """Serves /metrics (text exposition), /debug/stacks (pprof analog) and
    /healthz. With a `health_probe` callable, /healthz runs it per request
    (the gRPC-healthcheck self-probe analog, gpu plugin health.go:49-144)
    and returns 503 when it reports unhealthy."""

    def __init__(self, addr: str = "127.0.0.1", port: int = 0,
                 registry: Registry = DefaultRegistry,
                 health_probe=None):
        registry_ref = registry
        probe_ref = health_probe

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path == "/metrics":
                    body = registry_ref.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                elif self.path == "/debug/stacks":
                    path = debug.dump_stacks()
                    body = open(path, "rb").read()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                elif self.path == "/healthz":
                    healthy = True
                    detail = "ok"
                    if probe_ref is not None:
                        try:
                            healthy = bool(probe_ref())
                            detail = "ok" if healthy else "probe failed"
                        except Exception as e:  # noqa: BLE001
                            healthy, detail = False, str(e)
                    body = detail.encode()
                    self.send_response(200 if healthy else 503)
                else:
                    body = b"not found"
                    self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((addr, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="metrics-http")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# ---------------------------------------------------------------------------
# Control-plane instruments (sim scheduler + CEL compile cache)
# ---------------------------------------------------------------------------
# Defined here rather than in their consumer modules because two layers
# share them (simcluster.cel compiles, simcluster.scheduler evaluates and
# resyncs) and the bench/perf tier asserts on them cross-process — one
# canonical home keeps the gate names stable (SURVEY §10).

CEL_CACHE_HITS = DefaultRegistry.counter(
    "tpu_dra_cel_cache_hits",
    "CEL compile-cache lookups that found a cached program")
CEL_CACHE_MISSES = DefaultRegistry.counter(
    "tpu_dra_cel_cache_misses",
    "CEL compile-cache lookups that found nothing (a compile follows)")
CEL_COMPILES = DefaultRegistry.counter(
    "tpu_dra_cel_compiles",
    "CEL expressions actually tokenized+parsed; steady state this equals "
    "the number of DISTINCT selector sources seen (perf.sh gate)")
SCHED_FULL_RELISTS = DefaultRegistry.counter(
    "tpu_dra_sched_full_relists",
    "scheduler-level full rescans: poll-mode reconcile_once calls plus "
    "dirty-index resync fallbacks; steady-state event-driven target is 0")
SCHED_WATCH_EVENTS = DefaultRegistry.counter(
    "tpu_dra_sched_watch_events",
    "watch events applied by the scheduler, labeled by resource")
SCHED_PODS_BOUND = DefaultRegistry.counter(
    "tpu_dra_sched_pods_bound",
    "pods bound to a node by the sim scheduler")
SCHED_CLAIMS_GCED = DefaultRegistry.counter(
    "tpu_dra_sched_claims_gced",
    "template-owned ResourceClaims garbage-collected after pod death, "
    "labeled by path (event|sweep)")

# -- parallel scheduler core (multi-worker pool + sharded index +
# snapshot scans, SURVEY §15) ------------------------------------------------

SCHED_WORKERS = DefaultRegistry.gauge(
    "tpu_dra_sched_workers",
    "reconcile worker threads the scheduler's WorkQueue pool runs")
SCHED_SNAPSHOT_CONFLICTS = DefaultRegistry.counter(
    "tpu_dra_sched_snapshot_conflicts_total",
    "optimistic snapshot commits refused because the shard moved "
    "underneath the scan (another worker took a picked device, or the "
    "sched.snapshot_commit fault fired); each conflict re-scans against "
    "a fresh snapshot, bounded before backoff-requeue")
SCHED_SHARD_RESYNCS = DefaultRegistry.counter(
    "tpu_dra_sched_shard_resyncs_total",
    "allocation-index shards rebuilt by the guarded resync fallback "
    "(per-shard dirty flags: one divergent shard resyncs alone without "
    "blocking scans on the others)")
SCHED_EVICTIONS = DefaultRegistry.counter(
    "tpu_dra_sched_evictions_total",
    "claims evicted because an allocated device disappeared from the "
    "published inventory (chip quarantined/yanked, node lost), labeled "
    "by reason (device_lost|node_lost); every eviction releases through "
    "the claim deallocation write + mutation-cache pipeline and "
    "re-drives the owner pod")
# -- HA control plane (active-standby leases + takeover, SURVEY §22):
# defined here rather than in infra/leaderelect.py because the chaos
# matrix, bench failover phase and perf gates all read them
# cross-layer — same canonical-home rule as the scheduler instruments
# above. ---------------------------------------------------------------------

SCHED_LEADER = DefaultRegistry.gauge(
    "tpu_dra_sched_leader",
    "1 while this elector holds the scheduler lease, 0 while standby or "
    "after stepping down/deposal, labeled by identity — the failover "
    "dashboards' who-is-acting signal")
SCHED_LEASE_TRANSITIONS = DefaultRegistry.counter(
    "tpu_dra_sched_lease_transitions_total",
    "lease acquisitions (first grab + every takeover) observed by the "
    "electors of this process; each one bumps the fencing generation "
    "that deposed-leader claim-status writes are refused against")

WORKQUEUE_DEPTH = DefaultRegistry.gauge(
    "tpu_dra_workqueue_depth",
    "items queued (delay heap + per-key deferred) in a named WorkQueue, "
    "labeled by queue")
WORKQUEUE_BUSY = DefaultRegistry.gauge(
    "tpu_dra_workqueue_busy_workers",
    "pool workers currently processing an item, labeled by queue")

# -- ICI topology subsystem (tpu_dra.topology + the scheduler's
# topology-scored pick path, SURVEY §11) ------------------------------------

TOPO_ALLOCS = DefaultRegistry.counter(
    "tpu_dra_topo_allocations",
    "multi-chip device picks, labeled by outcome: contiguous (topology-"
    "scored cuboid), fallback (node publishes no usable topology -> "
    "first-fit), unplaceable (no contiguous cuboid fits the free set; "
    "the claim waits). Contiguity ratio = contiguous/(contiguous+fallback)")
TOPO_SCORE_SECONDS = DefaultRegistry.histogram(
    "tpu_dra_topo_score_seconds",
    "wall seconds spent on the topology path per multi-chip pick: "
    "placement scan+score plus the free-cuboid fragmentation observe",
    buckets=(0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
             0.01, 0.025, 0.05, 0.1, 0.5))
TOPO_FREE_CUBOID = DefaultRegistry.histogram(
    "tpu_dra_topo_free_cuboid_chips",
    "largest free cuboid (chips) remaining on the node after each "
    "topology-scored placement — the fragmentation observable",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))

# -- allocation -> mesh data-plane handoff (topology/meshexport +
# workloads/meshbuild, SURVEY §17) -------------------------------------------

MESH_BUILDS = DefaultRegistry.counter(
    "tpu_dra_mesh_builds_total",
    "allocation -> MeshPlan constructions, labeled by outcome: ok "
    "(contiguous cuboid, all-neighbor ring), fragmented (plan still "
    "builds but the modeled hop cost is above the cuboid floor), "
    "refused (rank/topology mismatch, duplicate or out-of-bounds "
    "coordinates — the loud-refusal contract)")
PSUM_BW = DefaultRegistry.histogram(
    "tpu_dra_psum_bandwidth_gbps",
    "measured all-reduce algorithm bandwidth (GB/s) per collective run "
    "on a driver-allocated mesh (the bench's psum phase and any "
    "launch_workload('allreduce') caller)",
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 200.0,
             400.0, 800.0))
PSUM_AB_DELTA = DefaultRegistry.gauge(
    "tpu_dra_psum_ab_delta_gbps",
    "modeled ICI bandwidth delta (contiguous cuboid minus deliberately "
    "fragmented placement of the same chip count) from the last "
    "placement-quality A/B — the bandwidth the topology scorer's "
    "contiguity preference buys, deterministic on the fake backend")

# -- drmc deterministic model checker (tpu_dra/analysis/drmc, SURVEY
# §13): exploration volume counters the hack/drmc.sh gate asserts on —
# defined here (not in the analysis package) for the same reason as the
# scheduler instruments above: the bench/CI tier reads them
# cross-process and the catalog is their one canonical home. ----------------

DRMC_SCHEDULES = DefaultRegistry.counter(
    "tpu_dra_drmc_schedules_total",
    "controlled-scheduler interleavings executed by the drmc explorer, "
    "labeled by scenario")
DRMC_CRASHPOINTS = DefaultRegistry.counter(
    "tpu_dra_drmc_crashpoints_total",
    "crash-point variants (post-op, torn, all-persisted) enumerated and "
    "recovered by the drmc crash engine, labeled by scenario")


class Timer:
    """Context manager observing elapsed seconds into a Histogram."""

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
