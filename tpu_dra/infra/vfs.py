"""Durable-op indirection: the seam the crash-point enumerator records.

Every write the project's crash story depends on — checkpoint slot
pwrites/truncates/fdatasyncs (tpuplugin/checkpoint.py), CDI spec
tmp+rename writes (cdi/handler.py), the node-global flock syscall
(infra/flock.py) — goes through this module instead of calling ``os``
directly. By default each function is a thin passthrough (same syscall,
same errors, no extra allocation), so production behavior is untouched.

``install(impl)`` swaps in a recording implementation: drmc's crash
enumerator (tpu_dra/analysis/drmc/crash.py) uses it to shadow per-file
synced-vs-volatile content, number every durable op, and simulate a
SIGKILL after any one of them — including torn variants of the last
write — then restores the on-disk crash image for recovery to chew on.

The indirection is deliberately NOT a class the callers hold: durable
ops are rare (a handful per prepare), module-function dispatch keeps
call sites greppable (``vfs.pwrite`` is the audit trail for "this write
is part of the durability contract"), and a single process-global
implementation matches the single-process crash model being simulated.
"""

from __future__ import annotations

import fcntl
import os
from typing import Optional


class VfsImpl:
    """Override points for a recording implementation. The default
    methods ARE the production behavior; a recorder must preserve the
    real side effects (drmc runs the real stack) while shadowing them."""

    def open_fd(self, path: str, flags: int, mode: int = 0o600) -> int:
        return os.open(path, flags, mode)

    def close_fd(self, fd: int) -> None:
        os.close(fd)

    def pwrite(self, fd: int, data: bytes, offset: int) -> int:
        return os.pwrite(fd, data, offset)

    def preallocate(self, fd: int, offset: int, length: int) -> None:
        """Zero-fill [offset, offset+length) so later appends land in
        already-allocated blocks and their fdatasync stays a pure data
        sync (checkpoint journal segments). Routed through pwrite so a
        recording implementation that only overrides the primitive ops
        still shadows the extension as a durable op."""
        off = 0
        zeros = b"\0" * min(length, 1 << 20)
        while off < length:
            chunk = zeros[:length - off]
            n = self.pwrite(fd, chunk, offset + off)
            if n <= 0:
                raise OSError(f"short preallocation write at {offset + off}")
            off += n

    def ftruncate(self, fd: int, length: int) -> None:
        os.ftruncate(fd, length)

    def fdatasync(self, fd: int) -> None:
        getattr(os, "fdatasync", os.fsync)(fd)

    def fsync(self, fd: int) -> None:
        os.fsync(fd)

    def fsync_dir(self, path: str) -> None:
        dfd = os.open(path or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def write_text(self, path: str, text: str) -> None:
        # os.open/os.write instead of the open() text wrapper: no
        # TextIOWrapper/buffering setup, ~35% cheaper per call — this
        # sits on the claim-spec hot path at batch size (SURVEY §14).
        # Looped: POSIX permits short writes (ENOSPC mid-buffer), and a
        # silently truncated spec renamed into place would hand the
        # container runtime invalid JSON behind a success.
        data = text.encode()
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            off = 0
            while off < len(data):
                n = os.write(fd, data[off:])
                if n <= 0:
                    raise OSError(f"short write to {path} at {off}")
                off += n
        finally:
            os.close(fd)

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def unlink(self, path: str) -> None:
        os.unlink(path)

    def flock(self, fd: int, op: int) -> None:
        fcntl.flock(fd, op)


_DEFAULT = VfsImpl()
_impl: VfsImpl = _DEFAULT


def install(impl: VfsImpl) -> None:
    """Route durable ops through `impl` (drmc crash recording). Not
    refcounted: exactly one recorder at a time, and a second install
    while one is active is a harness bug worth failing loudly on."""
    global _impl
    if _impl is not _DEFAULT:
        raise RuntimeError("vfs recorder already installed")
    _impl = impl


def uninstall() -> None:
    global _impl
    _impl = _DEFAULT


def installed() -> Optional[VfsImpl]:
    return None if _impl is _DEFAULT else _impl


# -- dispatch (the call-site surface) ---------------------------------------

def open_fd(path: str, flags: int, mode: int = 0o600) -> int:
    return _impl.open_fd(path, flags, mode)


def close_fd(fd: int) -> None:
    _impl.close_fd(fd)


def pwrite(fd: int, data: bytes, offset: int) -> int:
    return _impl.pwrite(fd, data, offset)


def preallocate(fd: int, offset: int, length: int) -> None:
    _impl.preallocate(fd, offset, length)


def ftruncate(fd: int, length: int) -> None:
    _impl.ftruncate(fd, length)


def fdatasync(fd: int) -> None:
    _impl.fdatasync(fd)


def fsync(fd: int) -> None:
    _impl.fsync(fd)


def fsync_dir(path: str) -> None:
    _impl.fsync_dir(path)


def write_text(path: str, text: str) -> None:
    _impl.write_text(path, text)


def replace(src: str, dst: str) -> None:
    _impl.replace(src, dst)


def unlink(path: str) -> None:
    _impl.unlink(path)


def flock(fd: int, op: int) -> None:
    _impl.flock(fd, op)
