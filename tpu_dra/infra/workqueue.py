"""Rate-limited work queue with per-key latest-wins retry semantics.

Reference: pkg/workqueue/workqueue.go:31-197 and jitterlimiter.go:32-67.
Retryable reconcile callbacks are enqueued with a key; when a newer item is
enqueued under the same key, a *failed* older item is forgotten instead of
retried (supersede, workqueue.go:173-189). Rate limiting combines per-item
exponential backoff with a global token bucket (DefaultPrepUnprepRateLimiter)
or adds relative jitter (DefaultCDDaemonRateLimiter) so a fleet of daemons
doesn't thundering-herd the API server.

The implementation is a threaded delay queue rather than a port of
client-go; semantics (AddRateLimited / Forget / NumRequeues / supersede)
are preserved.

**Worker pools** (SURVEY §15): ``start_workers(n)`` runs N consumer
threads against one queue with client-go's parallelism contract — two
items sharing a key are NEVER processed concurrently. A ready item
whose key is mid-process on another worker is *deferred* (parked in a
per-key side list, still absorbing ``dedupe=True`` enqueues — it has
not run yet, so the state-based reconcile contract holds) and
re-queued the instant the in-flight item completes. Keyless items are
never serialized. One worker (``run()``) degenerates to the original
single-consumer behavior exactly.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from tpu_dra.infra.trace import RECORDER as _FLIGHTREC


# ---------------------------------------------------------------------------
# drmc seam (tpu_dra/analysis/drmc): deterministic-scheduler hooks
# ---------------------------------------------------------------------------
# When installed, the model checker virtualizes the queue's condition
# variable for threads under controlled scheduling — wait() parks the
# task in the scheduler's model (a timed wait can always wake, so a
# waiting task stays schedulable as a timeout when nothing else can
# run) and notify() wakes modeled waiters — and sees enqueue/pop as
# yield points carrying the item key (the DPOR conflict label).
# Uncontrolled threads fall through to the real Condition, so a live
# process with a checker installed elsewhere keeps working.

_drmc = None


def set_drmc_hooks(hooks) -> None:
    global _drmc
    _drmc = hooks


def clear_drmc_hooks() -> None:
    global _drmc
    _drmc = None


# ---------------------------------------------------------------------------
# Rate limiters
# ---------------------------------------------------------------------------

class RateLimiter:
    def when(self, item_id: int) -> float:
        """Seconds to wait before (re)processing this item."""
        raise NotImplementedError

    def forget(self, item_id: int) -> None:
        pass

    def num_requeues(self, item_id: int) -> int:
        return 0


class ExponentialFailureRateLimiter(RateLimiter):
    """Per-item exponential backoff: base * 2^failures, capped."""

    def __init__(self, base_delay: float, max_delay: float):
        self._base = base_delay
        self._max = max_delay
        self._failures: Dict[int, int] = {}
        self._lock = threading.Lock()

    def when(self, item_id: int) -> float:
        with self._lock:
            n = self._failures.get(item_id, 0)
            self._failures[item_id] = n + 1
        return min(self._base * (2 ** n), self._max)

    def forget(self, item_id: int) -> None:
        with self._lock:
            self._failures.pop(item_id, None)

    def num_requeues(self, item_id: int) -> int:
        with self._lock:
            return self._failures.get(item_id, 0)


class BucketRateLimiter(RateLimiter):
    """Global token bucket (golang.org/x/time/rate analog): `qps` refills/s,
    `burst` capacity; when() reserves a token and returns the wait."""

    def __init__(self, qps: float, burst: int):
        self._qps = qps
        self._burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def when(self, item_id: int) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._burst, self._tokens + (now - self._last) * self._qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self._qps


class MaxOfRateLimiter(RateLimiter):
    """Pick the longest delay among limiters (workqueue.go:52-66)."""

    def __init__(self, *limiters: RateLimiter):
        self._limiters = limiters

    def when(self, item_id: int) -> float:
        return max(l.when(item_id) for l in self._limiters)

    def forget(self, item_id: int) -> None:
        for l in self._limiters:
            l.forget(item_id)

    def num_requeues(self, item_id: int) -> int:
        return max(l.num_requeues(item_id) for l in self._limiters)


class JitterRateLimiter(RateLimiter):
    """Wrap an inner limiter with +/- factor/2 relative jitter
    (jitterlimiter.go:32-67)."""

    def __init__(self, inner: RateLimiter, factor: float):
        if factor >= 1.0:
            raise ValueError("jitter factor must be < 1.0")
        self._inner = inner
        self._factor = factor

    def when(self, item_id: int) -> float:
        d = self._inner.when(item_id)
        return max(0.0, d + d * self._factor * (random.random() - 0.5))

    def forget(self, item_id: int) -> None:
        self._inner.forget(item_id)

    def num_requeues(self, item_id: int) -> int:
        return self._inner.num_requeues(item_id)


def default_prep_unprep_rate_limiter() -> RateLimiter:
    """250ms–3s per-item expo + global 5/s bucket with burst 10
    (workqueue.go DefaultPrepUnprepRateLimiter)."""
    return MaxOfRateLimiter(
        ExponentialFailureRateLimiter(0.250, 3.0),
        BucketRateLimiter(qps=5, burst=10),
    )


def default_cd_daemon_rate_limiter() -> RateLimiter:
    """5ms–6s expo with 0.5 relative jitter (DefaultCDDaemonRateLimiter)."""
    return JitterRateLimiter(ExponentialFailureRateLimiter(0.005, 6.0), 0.5)


def default_controller_rate_limiter() -> RateLimiter:
    """client-go DefaultTypedControllerRateLimiter analog: 5ms–1000s expo +
    10/s bucket with burst 100."""
    return MaxOfRateLimiter(
        ExponentialFailureRateLimiter(0.005, 1000.0),
        BucketRateLimiter(qps=10, burst=100),
    )


# ---------------------------------------------------------------------------
# Work queue
# ---------------------------------------------------------------------------

@dataclass
class WorkItem:
    key: str
    obj: Any
    callback: Callable[[Any], None]
    item_id: int = field(default_factory=itertools.count().__next__)
    # Registered in _queued_keys (dedupe bookkeeping)? Failure-backoff
    # retries are NOT: a retry parked seconds out must never absorb a
    # fresh immediate enqueue — the new item runs now, the retry later
    # no-ops (state-based reconcile).
    counted: bool = False


class WorkQueue:
    """Threaded delay queue; run() processes items until shutdown().

    Failed callbacks (those that raise) are re-enqueued rate-limited unless a
    newer item with the same key has been enqueued since — then the failure
    is forgotten ("latest wins", workqueue.go:173-189). Exceptions raised by
    callbacks are treated as expected retryable errors in an eventually
    consistent system and not re-raised.
    """

    def __init__(self, rate_limiter: Optional[RateLimiter] = None,
                 log: Optional[Callable[[str], None]] = None,
                 name: str = ""):
        self._rl = rate_limiter or default_controller_rate_limiter()
        self._heap: list = []  # (ready_at, seq, WorkItem)
        self._seq = itertools.count()
        # Condition over an EXPLICIT Lock (not the default RLock the
        # Condition would allocate inside threading's own frame): a lock
        # created here, in tpu_dra code, is witnessable — the lock-order
        # witness sees the queue's critical sections and drmc can model
        # them. The queue never re-enters its own condition, so a plain
        # Lock is sufficient.
        self._cond = threading.Condition(threading.Lock())
        self._active_ops: Dict[str, WorkItem] = {}
        # key -> number of items still queued (in the heap or deferred,
        # not yet handed to a worker); backs dedupe=True below.
        self._queued_keys: Dict[str, int] = {}
        # Per-key serialization state for worker pools: keys a worker is
        # processing right now, and ready items deferred because their
        # key was in flight (re-queued on release).
        self._processing: Dict[str, WorkItem] = {}
        self._deferred: Dict[str, list] = {}
        self._busy = 0
        self._shutdown = False
        self._log = log or (lambda msg: None)
        # Named queues export depth/busy-worker gauges (unnamed queues —
        # short-lived test fixtures — stay out of the registry's labels).
        self._name = name
        self._depth_gauge = self._busy_gauge = None
        if name:
            from tpu_dra.infra.metrics import WORKQUEUE_BUSY, WORKQUEUE_DEPTH
            self._depth_gauge = WORKQUEUE_DEPTH
            self._busy_gauge = WORKQUEUE_BUSY

    # -- producers ----------------------------------------------------------

    def enqueue(self, obj: Any, callback: Callable[[Any], None],
                key: str = "", after: Optional[float] = None,
                dedupe: bool = False) -> None:
        """after: explicit delay in seconds, overriding the rate limiter —
        for time-based re-evaluation (settle windows) rather than
        failure backoff.

        dedupe=True gives client-go Add() semantics for keyed items: a
        key already sitting in the queue absorbs the enqueue (the queued
        item will observe the latest state when it runs — callbacks are
        state-based reconciles by contract), while a key currently
        PROCESSING enqueues normally so a change racing the reconcile is
        never lost. Failure-backoff retries never absorb (WorkItem
        .counted): a retry parked behind exponential backoff must not
        delay reaction to a fresh event. Event-storm fan-in (N
        capacity-freed events all nudging the same pending pods)
        collapses to one queued item per key instead of N."""
        if _FLIGHTREC.enabled:
            # Queue events are flight-recorder evidence (SURVEY §19): a
            # wedge dump shows what was queued when. Recorded OUTSIDE
            # _cond — the ring append is lock-free, and extending the
            # queue's critical section by even ~1µs per item is a
            # measurable tax on a contended 4-worker pool.
            _FLIGHTREC.record_wq(self._name or "?", "add", key)
        with self._cond:
            self._yield_op("queue.add", key)
            if dedupe and key and self._queued_keys.get(key, 0) > 0:
                return
            item = WorkItem(key=key, obj=obj, callback=callback)
            if key:
                self._active_ops[key] = item
                item.counted = True
                self._queued_keys[key] = self._queued_keys.get(key, 0) + 1
            self._push_locked(item, after=after)
            self._observe_locked()
            self._notify()

    def _push_locked(self, item: WorkItem,
                     after: Optional[float] = None) -> None:
        delay = self._rl.when(item.item_id) if after is None else after
        heapq.heappush(self._heap, (time.monotonic() + delay, next(self._seq), item))

    # -- drmc indirections ---------------------------------------------------

    def _yield_op(self, kind: str, key: str) -> None:
        hooks = _drmc
        if hooks is not None:
            hooks.yield_op(kind, key)

    def _notify(self, all_waiters: bool = False) -> None:
        hooks = _drmc
        if hooks is not None and hooks.notify(self._cond, all_waiters):
            return
        if all_waiters:
            self._cond.notify_all()
        else:
            self._cond.notify()

    def _wait(self, timeout: float) -> None:
        hooks = _drmc
        if hooks is not None and hooks.wait(self._cond, timeout):
            return
        self._cond.wait(timeout=timeout)

    # -- consumer -----------------------------------------------------------

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Process items until shutdown() (or stop_event set)."""
        while True:
            item = self._get(stop_event)
            if item is None:
                return
            self._process(item)

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True, name="workqueue")
        t.start()
        return t

    def start_workers(self, n: int,
                      stop_event: Optional[threading.Event] = None
                      ) -> list:
        """The worker pool: N consumer threads over this queue with
        per-key serialization (module docstring). Returns the threads;
        join them after shutdown()/stop_event for a clean stop."""
        threads = []
        for i in range(n):
            t = threading.Thread(target=self.run, args=(stop_event,),
                                 daemon=True,
                                 name=f"workqueue-{self._name or 'pool'}-{i}")
            t.start()
            threads.append(t)
        return threads

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._notify(all_waiters=True)

    def _get(self, stop_event: Optional[threading.Event]) -> Optional[WorkItem]:
        with self._cond:
            while True:
                if self._shutdown or (stop_event is not None and stop_event.is_set()):
                    return None
                now = time.monotonic()
                handed = None
                while self._heap and self._heap[0][0] <= now:
                    _, _, item = heapq.heappop(self._heap)
                    if item.key and item.key in self._processing:
                        # Per-key serialization: this key is mid-process
                        # on another worker. Defer — the item keeps its
                        # dedupe registration (it has not run, so it
                        # still absorbs same-key enqueues) and is
                        # re-queued when the in-flight item completes.
                        self._deferred.setdefault(item.key, []).append(item)
                        continue
                    handed = item
                    break
                if handed is not None:
                    self._yield_op("queue.get", handed.key)
                    if handed.key:
                        self._processing[handed.key] = handed
                        if handed.counted:
                            handed.counted = False  # a retry re-push stays
                            #   uncounted: dedupe must not absorb into it
                            n = self._queued_keys.get(handed.key, 0) - 1
                            if n > 0:
                                self._queued_keys[handed.key] = n
                            else:
                                self._queued_keys.pop(handed.key, None)
                    self._busy += 1
                    self._observe_locked()
                    return handed
                if self._heap:
                    self._wait(min(self._heap[0][0] - now, 0.5))
                else:
                    self._wait(0.5)

    def _release_key_locked(self, item: WorkItem) -> None:
        """End of this item's processing: free its key and re-queue any
        ready items that were deferred behind it (one notify per item so
        idle pool workers pick them up immediately)."""
        self._busy -= 1
        if item.key:
            if self._processing.get(item.key) is item:
                del self._processing[item.key]
            for deferred in self._deferred.pop(item.key, ()):
                heapq.heappush(self._heap,
                               (time.monotonic(), next(self._seq), deferred))
                self._notify()
        self._observe_locked()

    def _process(self, item: WorkItem) -> None:
        attempts = self._rl.num_requeues(item.item_id)
        if _FLIGHTREC.enabled:
            # The "get" evidence, outside _cond (see enqueue): stamped
            # at processing start, which is what add->get gap analysis
            # in a dump actually wants.
            _FLIGHTREC.record_wq(self._name or "?", "get", item.key)
        try:
            item.callback(item.obj)
        except Exception as e:  # noqa: BLE001 — retryable by contract
            self._log(f"reconcile: {e} (attempt {attempts})")
            with self._cond:
                self._release_key_locked(item)
                current = self._active_ops.get(item.key)
                if item.key and current is not item:
                    # Superseded — a newer item under this key is either
                    # still pending (current is that item) or already
                    # COMPLETED (current is None: success deletes the
                    # entry). Both mean this failure is obsolete; the
                    # None case previously re-enqueued the stale item,
                    # which then retried forever against state the newer
                    # item had already reconciled.
                    self._log(f"not re-enqueueing '{item.key}': superseded")
                    self._rl.forget(item.item_id)
                else:
                    self._push_locked(item)
                    self._notify()
            return
        with self._cond:
            self._release_key_locked(item)
            if item.key and self._active_ops.get(item.key) is item:
                del self._active_ops[item.key]
            self._rl.forget(item.item_id)

    def _observe_locked(self) -> None:
        if self._depth_gauge is not None:
            labels = {"queue": self._name}
            self._depth_gauge.set(
                len(self._heap) + sum(len(v) for v in
                                      self._deferred.values()),
                labels=labels)
            self._busy_gauge.set(self._busy, labels=labels)

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        with self._cond:
            return (len(self._heap)
                    + sum(len(v) for v in self._deferred.values()))
