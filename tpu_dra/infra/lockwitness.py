"""Runtime lock-order witness: the dynamic half of dralint.

Static rules (tpu_dra/analysis) police what is lexically checkable;
what they cannot see is the ACQUISITION ORDER two threads impose on a
pair of locks. This module is a lockdep-style witness: an opt-in
instrumented Lock/RLock that records, per thread, the stack of held
locks and adds an edge ``A -> B`` to a process-global graph whenever B
is acquired while A is held. A cycle in that graph is a potential
deadlock — two threads CAN interleave into it even if this run did not
— and is recorded as a violation the moment the closing edge appears.
Hold times are tracked per lock class so "I/O crept under a lock"
pathologies show up as outliers even when no cycle forms.

Lock identity is the CREATION SITE (``file:line`` of the allocation),
not the instance: a scheduler with 5 informers has 5 instances of one
lock class, and ordering rules are per-class (as in lockdep). Nested
acquisition of two instances of the SAME class (per-chip locks taken
in sorted order) is recorded separately as a self-nest, not a cycle —
ordered same-class acquisition is the holder's documented
responsibility, the witness can't prove the sort.

``install()`` (refcounted) monkeypatches ``threading.Lock`` /
``threading.RLock`` so locks *subsequently created by tpu_dra code*
are witnessed; stdlib- and third-party-created locks (Condition
internals, JAX) pass through raw. The chaos harnesses install it for
every walk and assert an acyclic graph at quiesce; ``hack/race.sh``
sets ``TPU_DRA_LOCK_WITNESS=1`` so the threaded suites run witnessed
too (tests/conftest.py fails the session on cycles).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_real_lock = threading.Lock
_real_rlock = threading.RLock


@dataclass
class _ClassStats:
    acquisitions: int = 0
    max_hold_s: float = 0.0
    self_nests: int = 0


@dataclass
class _Edge:
    thread: str
    count: int = 0


class LockWitness:
    """Process-global acquisition-order graph + per-class hold stats."""

    def __init__(self):
        self._graph_lock = _real_lock()
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self._stats: Dict[str, _ClassStats] = {}
        self._violations: List[str] = []
        self._seen_cycles: Set[Tuple[str, ...]] = set()
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> List[dict]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- events from witnessed locks ----------------------------------------

    def acquired(self, key: str, instance: int) -> None:
        held = self._held()
        for entry in held:
            if entry["instance"] == instance:
                entry["depth"] += 1  # RLock reentry: no new edge, no push
                return
        new_edges: List[Tuple[str, str]] = []
        self_nest = False
        for entry in held:
            if entry["key"] == key:
                self_nest = True
            else:
                new_edges.append((entry["key"], key))
        held.append({"key": key, "instance": instance, "depth": 1,
                     "t0": time.monotonic()})
        if not (new_edges or self_nest):
            with self._graph_lock:
                self._stats.setdefault(key, _ClassStats()).acquisitions += 1
            return
        tname = threading.current_thread().name
        with self._graph_lock:
            st = self._stats.setdefault(key, _ClassStats())
            st.acquisitions += 1
            if self_nest:
                st.self_nests += 1
            for src, dst in new_edges:
                edge = self._edges.get((src, dst))
                if edge is None:
                    self._edges[(src, dst)] = _Edge(thread=tname, count=1)
                    self._check_cycle_locked(src, dst)
                else:
                    edge.count += 1

    def released(self, key: str, instance: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry["instance"] == instance:
                entry["depth"] -= 1
                if entry["depth"] == 0:
                    dt = time.monotonic() - entry["t0"]
                    del held[i]
                    with self._graph_lock:
                        st = self._stats.setdefault(key, _ClassStats())
                        if dt > st.max_hold_s:
                            st.max_hold_s = dt
                return
        # release of a lock acquired before install()/reset(): ignore

    def force_release(self, key: str, instance: int) -> int:
        """Condition._release_save seam: the inner RLock is FULLY
        released regardless of recursion depth — drop the whole entry
        (closing its hold window) and return the depth so
        force_acquire can restore it after the wait."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry["instance"] == instance:
                dt = time.monotonic() - entry["t0"]
                del held[i]
                with self._graph_lock:
                    st = self._stats.setdefault(key, _ClassStats())
                    if dt > st.max_hold_s:
                        st.max_hold_s = dt
                return entry["depth"]
        return 1

    def force_acquire(self, key: str, instance: int, depth: int) -> None:
        """Condition._acquire_restore seam: re-enter at full depth."""
        self.acquired(key, instance)
        for entry in self._held():
            if entry["instance"] == instance:
                entry["depth"] = depth
                return

    # -- cycle detection ------------------------------------------------------

    def _check_cycle_locked(self, src: str, dst: str) -> None:
        """A new edge src->dst closes a cycle iff dst already reaches
        src. Runs under self._graph_lock at edge-insertion time, so the first
        interleaving that COULD deadlock is reported even if this run
        sailed through."""
        path = self._find_path_locked(dst, src)
        if path is None:
            return
        cycle = [src] + path  # src -> dst -> ... -> src
        nodes = cycle[:-1]
        canon = min(tuple(nodes[i:] + nodes[:i])
                    for i in range(len(nodes)))
        if canon in self._seen_cycles:
            return
        self._seen_cycles.add(canon)
        self._violations.append(
            "lock-order cycle (potential deadlock): "
            + " -> ".join(cycle)
            + f" (closing edge {src} -> {dst} added by thread "
            + threading.current_thread().name + ")")

    def _find_path_locked(self, start: str,
                          goal: str) -> Optional[List[str]]:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        seen = {start}
        adj: Dict[str, List[str]] = {}
        for (s, d) in self._edges:
            adj.setdefault(s, []).append(d)
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- reporting ------------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._graph_lock:
            return {k: e.count for k, e in self._edges.items()}

    def cycles(self) -> List[str]:
        with self._graph_lock:
            return list(self._violations)

    @staticmethod
    def _format_outlier(key: str, st: _ClassStats,
                        max_hold_s: float) -> str:
        return (f"lock {key} held for {st.max_hold_s * 1e3:.1f}ms "
                f"(> {max_hold_s * 1e3:.0f}ms outlier threshold; "
                "blocking work crept under a data lock?)")

    def _outliers_locked(self, max_hold_s: float,
                         base: Optional[Dict[str, float]] = None
                         ) -> List[str]:
        """Outlier lines; with `base`, only classes whose max hold GREW
        past the threshold since that snapshot. Caller holds _graph_lock."""
        return [self._format_outlier(key, st, max_hold_s)
                for key, st in sorted(self._stats.items())
                if st.max_hold_s > max_hold_s
                and (base is None or st.max_hold_s > base.get(key, 0.0))]

    def hold_outliers(self, max_hold_s: float) -> List[str]:
        with self._graph_lock:
            return self._outliers_locked(max_hold_s)

    def violations(self, max_hold_s: Optional[float] = None) -> List[str]:
        """Cycles (always) plus hold-time outliers (when a threshold is
        given) — the chaos-invariant seam."""
        out = self.cycles()
        if max_hold_s is not None:
            out.extend(self.hold_outliers(max_hold_s))
        return out

    def snapshot(self) -> Dict:
        """Opaque window marker for violations_since: under a
        session-level install (TPU_DRA_LOCK_WITNESS=1) the graph is
        never reset, so a harness must report only what ITS walk added."""
        with self._graph_lock:
            return {"cycles": len(self._violations),
                    "max_hold": {k: s.max_hold_s
                                 for k, s in self._stats.items()}}

    def violations_since(self, snap: Dict,
                         max_hold_s: Optional[float] = None) -> List[str]:
        """violations() restricted to what happened after `snap`:
        cycles recorded since, plus classes whose max hold GREW past
        the threshold inside the window (a pre-window outlier whose max
        did not move is someone else's violation)."""
        base = snap.get("max_hold", {})
        with self._graph_lock:
            out = list(self._violations[snap.get("cycles", 0):])
            if max_hold_s is not None:
                out.extend(self._outliers_locked(max_hold_s, base=base))
        return out

    def stats(self) -> Dict[str, Dict]:
        with self._graph_lock:
            return {k: {"acquisitions": s.acquisitions,
                        "max_hold_ms": round(s.max_hold_s * 1e3, 3),
                        "self_nests": s.self_nests}
                    for k, s in sorted(self._stats.items())}

    def reset(self) -> None:
        """Drop graph + stats (NOT per-thread held stacks: locks held
        across a reset simply stop contributing edges)."""
        with self._graph_lock:
            self._edges.clear()
            self._stats.clear()
            self._violations.clear()
            self._seen_cycles.clear()


WITNESS = LockWitness()


# ---------------------------------------------------------------------------
# Edge export (the draracer observed⊆static cross-validation seam)
# ---------------------------------------------------------------------------
# A chaos matrix, a drmc exploration, or a witnessed pytest session
# dumps the edge set it OBSERVED; ``python -m tpu_dra.analysis
# --check-witness FILE`` then asserts every observed edge is in the
# static lock-order graph (raceanalysis R11). Exports MERGE: several
# processes (the 25-seed matrix, then the soak, then drmc) accumulate
# into one file, so the gate checks the union of everything that ran.
# ``TPU_DRA_LOCK_WITNESS_EXPORT=<path>`` makes the export automatic at
# the final uninstall() of each generation (chaos/drmc harness close)
# and at witnessed-session exit (tests/conftest.py).

EXPORT_ENV = "TPU_DRA_LOCK_WITNESS_EXPORT"

# (path, frozenset(edges)) of the last auto-export: drmc installs and
# uninstalls around EVERY explored schedule, and a read-merge-rewrite
# per schedule would spend deadline-bounded exploration time on
# redundant IO — the refcount-zero flush skips when nothing changed.
_last_export: Optional[Tuple[str, frozenset]] = None


def export_edges(path: Optional[str] = None,
                 only_if_changed: bool = False) -> Optional[str]:
    """Merge the witness's observed edge set into the JSON file at
    `path` (default: $TPU_DRA_LOCK_WITNESS_EXPORT; no-op returning None
    when neither names a destination). Best-effort: an unwritable
    export path must not take down the harness that observed the
    edges — the gate reading the file is where absence gets loud."""
    global _last_export
    path = path or os.environ.get(EXPORT_ENV)
    if not path:
        return None
    edges = {(s, d) for (s, d) in WITNESS.edges()}
    own = frozenset(edges)  # pre-merge: the signature is OUR edges only
    if only_if_changed and _last_export == (path, own):
        return path
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        for e in doc.get("edges", ()):
            if isinstance(e, list) and len(e) == 2:
                edges.add((e[0], e[1]))
    except (OSError, ValueError):
        pass
    # Tmp + rename: a failed write (ENOSPC) must leave the previous
    # accumulation intact — truncating it in place would let the NEXT
    # exporter silently restart the merge from its own edges alone and
    # hand the observed⊆static gate a shrunken observed set.
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"edges": sorted(list(e) for e in edges)}, fh,
                      indent=0)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    _last_export = (path, own)
    return path


def load_edges(path: str) -> List[Tuple[str, str]]:
    """The exported edge set, as (src, dst) creation-site pairs."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return [(e[0], e[1]) for e in doc.get("edges", ())
            if isinstance(e, list) and len(e) == 2]


# ---------------------------------------------------------------------------
# Yield-point hook (drmc's controlled-scheduler seam)
# ---------------------------------------------------------------------------
# The witness's instrumentation points double as the deterministic model
# checker's yield points (tpu_dra/analysis/drmc): when a hook is set,
# every witnessed acquire/release first reports to it. The hook decides
# whether the calling thread is under controlled scheduling — for a
# controlled thread, "lock.acquire" BLOCKS until the cooperative
# scheduler grants the op (and guarantees the lock is model-free, so
# the real acquire below cannot block); uncontrolled threads pass
# through untouched. Events:
#   lock.acquire  — before the real acquire (the schedulable point)
#   lock.acquired — after a successful acquire (model bookkeeping only)
#   lock.release  — before the real release (model-release on grant)

_yield_hook = None


def set_yield_hook(fn) -> None:
    global _yield_hook
    _yield_hook = fn


def clear_yield_hook() -> None:
    global _yield_hook
    _yield_hook = None


# ---------------------------------------------------------------------------
# Instrumented locks
# ---------------------------------------------------------------------------

class _WitnessBase:
    """Wraps a real lock; reports acquire/release to WITNESS. Undeclared
    attributes delegate to the inner lock so Condition & friends keep
    working when handed one explicitly."""

    def __init__(self, inner, key: str):
        self._inner = inner
        self._key = key

    def acquire(self, blocking: bool = True, timeout: float = -1):
        hook = _yield_hook
        if hook is not None:
            hook("lock.acquire", self._key, id(self), blocking)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if hook is not None:
                hook("lock.acquired", self._key, id(self), blocking)
            WITNESS.acquired(self._key, id(self))
        return ok

    def release(self) -> None:
        hook = _yield_hook
        if hook is not None:
            hook("lock.release", self._key, id(self), True)
        self._inner.release()
        WITNESS.released(self._key, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._key} {self._inner!r}>"


class WitnessLock(_WitnessBase):
    def locked(self) -> bool:
        return self._inner.locked()


class WitnessRLock(_WitnessBase):
    # threading.Condition probes these when handed an RLock explicitly.
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        hook = _yield_hook
        if hook is not None:
            # Full-depth release (cond.wait entry): the model must drop
            # the whole ownership or a controlled sibling could never
            # acquire past the "held" entry of a parked waiter.
            hook("lock.release_save", self._key, id(self), True)
        state = self._inner._release_save()
        # The inner RLock is now FULLY released whatever the recursion
        # depth: close the hold window entirely, or a reentrant
        # cond.wait() would be booked as one long lock hold.
        depth = WITNESS.force_release(self._key, id(self))
        return (state, depth)

    def _acquire_restore(self, state) -> None:
        hook = _yield_hook
        if hook is not None:
            hook("lock.acquire", self._key, id(self), True)
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        if hook is not None:
            hook("lock.acquired", self._key, id(self), True)
        WITNESS.force_acquire(self._key, id(self), depth)


# ---------------------------------------------------------------------------
# Opt-in install (refcounted monkeypatch)
# ---------------------------------------------------------------------------

_install_mu = _real_lock()
_install_count = 0


def _creation_key(depth: int = 2) -> Optional[str]:
    """``file:line`` of the tpu_dra frame allocating the lock, or None
    for foreign code (left unwitnessed)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    fn = frame.f_code.co_filename
    if "tpu_dra" not in fn or "lockwitness" in fn:
        return None
    idx = fn.rfind("tpu_dra")
    return f"{fn[idx:]}:{frame.f_lineno}"


def _lock_factory():
    key = _creation_key()
    if key is None:
        return _real_lock()
    return WitnessLock(_real_lock(), key)


def _rlock_factory():
    key = _creation_key()
    if key is None:
        return _real_rlock()
    return WitnessRLock(_real_rlock(), key)


def install(reset: bool = True) -> None:
    """Start witnessing locks created from here on by tpu_dra code.
    Refcounted: nested harnesses install/uninstall freely; the first
    install of a generation resets the graph (unless reset=False)."""
    global _install_count
    with _install_mu:
        if _install_count == 0:
            if reset:
                WITNESS.reset()
            threading.Lock = _lock_factory
            threading.RLock = _rlock_factory
        _install_count += 1


def uninstall() -> None:
    global _install_count
    with _install_mu:
        if _install_count == 0:
            return
        _install_count -= 1
        if _install_count == 0:
            threading.Lock = _real_lock
            threading.RLock = _real_rlock
            last_out = True
        else:
            last_out = False
    if last_out:
        # The generation's graph is complete: flush it for the
        # observed⊆static gate (no-op unless the env names a file;
        # skipped when the merged edge set already matches the last
        # flush — drmc uninstalls once per explored schedule).
        export_edges(only_if_changed=True)


def installed() -> bool:
    with _install_mu:
        return _install_count > 0
