"""Polling non-blocking flock(2) with timeout and cancellation.

Reference: pkg/flock/flock.go:28-133. Guards prepare/unprepare node-globally:
during a rolling driver upgrade two plugin pods briefly coexist on one node
and must never interleave Prepare/Unprepare (driver.go:166-215 acquires this
around every claim operation). Non-blocking + poll (rather than a blocking
flock) keeps the timeout and cancel semantics portable.
"""

from __future__ import annotations

import errno
import fcntl
import os
import threading
import time
from typing import Optional

from tpu_dra.infra import vfs


class FlockTimeout(TimeoutError):
    pass


class Flock:
    def __init__(self, path: str, poll_interval: float = 0.1):
        self._path = path
        self._poll = poll_interval
        self._fd: Optional[int] = None
        self._tlock = threading.Lock()  # in-process serialization

    @property
    def path(self) -> str:
        return self._path

    def acquire(self, timeout: float = 10.0,
                cancel: Optional[threading.Event] = None) -> None:
        """Acquire or raise FlockTimeout. Re-opens the file each attempt so a
        deleted lock file doesn't wedge us holding a stale inode."""
        deadline = time.monotonic() + timeout
        if not self._tlock.acquire(timeout=timeout):
            raise FlockTimeout(f"in-process lock on {self._path} not acquired "
                               f"within {timeout}s")
        try:
            while True:
                if cancel is not None and cancel.is_set():
                    raise FlockTimeout(f"lock acquisition on {self._path} cancelled")
                fd = vfs.open_fd(self._path, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    # Through the vfs seam: the enumerator treats the
                    # acquire as a crash point — an flock dies with its
                    # holder, so recovery must simply re-acquire.
                    vfs.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    return
                except OSError as e:
                    vfs.close_fd(fd)
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                except BaseException:
                    # A simulated crash (drmc CrashPoint) fired inside
                    # the flock syscall seam: close the fd — process
                    # death would have — or the exclusive lock leaks
                    # into the long-lived harness process.
                    vfs.close_fd(fd)
                    raise
                if time.monotonic() >= deadline:
                    raise FlockTimeout(
                        f"flock on {self._path} not acquired within {timeout}s")
                time.sleep(self._poll)
        except BaseException:
            self._tlock.release()
            raise

    def release(self) -> None:
        # Nested finally: the unlock op can raise through the vfs seam
        # (drmc crash point on LOCK_UN) — the fd close and the
        # in-process serializer release must both still happen, or the
        # next acquire on this instance wedges on _tlock.
        try:
            if self._fd is not None:
                try:
                    vfs.flock(self._fd, fcntl.LOCK_UN)
                finally:
                    vfs.close_fd(self._fd)
                    self._fd = None
        finally:
            self._tlock.release()

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
