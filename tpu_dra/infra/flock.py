"""Polling non-blocking flock(2) with timeout and cancellation.

Reference: pkg/flock/flock.go:28-133. Guards prepare/unprepare node-globally:
during a rolling driver upgrade two plugin pods briefly coexist on one node
and must never interleave Prepare/Unprepare (driver.go:166-215 acquires this
around every claim operation). Non-blocking + poll (rather than a blocking
flock) keeps the timeout and cancel semantics portable.
"""

from __future__ import annotations

import errno
import fcntl
import os
import threading
import time
from typing import Optional


class FlockTimeout(TimeoutError):
    pass


class Flock:
    def __init__(self, path: str, poll_interval: float = 0.1):
        self._path = path
        self._poll = poll_interval
        self._fd: Optional[int] = None
        self._tlock = threading.Lock()  # in-process serialization

    @property
    def path(self) -> str:
        return self._path

    def acquire(self, timeout: float = 10.0,
                cancel: Optional[threading.Event] = None) -> None:
        """Acquire or raise FlockTimeout. Re-opens the file each attempt so a
        deleted lock file doesn't wedge us holding a stale inode."""
        deadline = time.monotonic() + timeout
        if not self._tlock.acquire(timeout=timeout):
            raise FlockTimeout(f"in-process lock on {self._path} not acquired "
                               f"within {timeout}s")
        try:
            while True:
                if cancel is not None and cancel.is_set():
                    raise FlockTimeout(f"lock acquisition on {self._path} cancelled")
                fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    return
                except OSError as e:
                    os.close(fd)
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                if time.monotonic() >= deadline:
                    raise FlockTimeout(
                        f"flock on {self._path} not acquired within {timeout}s")
                time.sleep(self._poll)
        except BaseException:
            self._tlock.release()
            raise

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None
        self._tlock.release()

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
