"""Polling non-blocking flock(2) with timeout and cancellation.

Reference: pkg/flock/flock.go:28-133. Guards prepare/unprepare node-globally:
during a rolling driver upgrade two plugin pods briefly coexist on one node
and must never interleave Prepare/Unprepare (driver.go:166-215 acquires this
around every claim operation). Non-blocking + poll (rather than a blocking
flock) keeps the timeout and cancel semantics portable.
"""

from __future__ import annotations

import errno
import fcntl
import os
import threading
import time
from typing import Optional

from tpu_dra.infra import vfs


class FlockTimeout(TimeoutError):
    pass


class Flock:
    def __init__(self, path: str, poll_interval: float = 0.1):
        # GUARDED_BY: none — immutable after construction
        self._path = path
        self._poll = poll_interval
        self._fd: Optional[int] = None
        self._tlock = threading.Lock()  # in-process serialization

    @property
    def path(self) -> str:
        return self._path

    def acquire(self, timeout: float = 10.0,
                cancel: Optional[threading.Event] = None) -> None:
        """Acquire or raise FlockTimeout. Re-opens the file each attempt so a
        deleted lock file doesn't wedge us holding a stale inode."""
        deadline = time.monotonic() + timeout
        if not self._tlock.acquire(timeout=timeout):
            raise FlockTimeout(f"in-process lock on {self._path} not acquired "
                               f"within {timeout}s")
        try:
            while True:
                if cancel is not None and cancel.is_set():
                    raise FlockTimeout(f"lock acquisition on {self._path} cancelled")
                fd = vfs.open_fd(self._path, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    # Through the vfs seam: the enumerator treats the
                    # acquire as a crash point — an flock dies with its
                    # holder, so recovery must simply re-acquire.
                    vfs.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    return
                except OSError as e:
                    vfs.close_fd(fd)
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                except BaseException:
                    # A simulated crash (drmc CrashPoint) fired inside
                    # the flock syscall seam: close the fd — process
                    # death would have — or the exclusive lock leaks
                    # into the long-lived harness process.
                    vfs.close_fd(fd)
                    raise
                if time.monotonic() >= deadline:
                    raise FlockTimeout(
                        f"flock on {self._path} not acquired within {timeout}s")
                time.sleep(self._poll)
        except BaseException:
            self._tlock.release()
            raise

    def release(self) -> None:
        # Nested finally: the unlock op can raise through the vfs seam
        # (drmc crash point on LOCK_UN) — the fd close and the
        # in-process serializer release must both still happen, or the
        # next acquire on this instance wedges on _tlock.
        try:
            if self._fd is not None:
                try:
                    vfs.flock(self._fd, fcntl.LOCK_UN)
                finally:
                    vfs.close_fd(self._fd)
                    self._fd = None
        finally:
            self._tlock.release()

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class SharedFlock:
    """In-process shared ownership over a node-global Flock.

    The flock guards against a SECOND PROCESS (rolling-upgrade driver
    pod) interleaving prepare/unprepare — concurrent RPC threads of ONE
    process are already serialized where it matters (per-claim-set
    pipeline ordering + DeviceState's internal locking), so they may
    share the file lock: the first thread in acquires it, late joiners
    just bump a refcount, and the last thread out releases it. Without
    this, the pipelined server would re-serialize every RPC on the
    flock and the cross-RPC group commit could never coalesce.

    Distinct threads may acquire and release (the underlying
    threading.Lock inside Flock is not owner-checked), which is exactly
    the pattern here.

    Fairness: under sustained RPC traffic, late joiners could keep the
    refcount above zero forever and the OS flock would never drop — a
    rolling-upgrade peer process would starve past its acquire timeout.
    So a continuous shared hold is BOUNDED (`max_shared_hold_s`): once
    exceeded, new joiners drain — they wait for the current holders to
    finish and the real flock to be released/reacquired, giving the
    competing process its handoff window (the same window the
    pre-pipeline flock-per-RPC behavior provided between every RPC)."""

    def __init__(self, flock: Flock, max_shared_hold_s: float = 5.0):
        self._flock = flock
        self._max_shared_hold_s = max_shared_hold_s
        # Condition over an explicit Lock created in this frame so the
        # lock witness instruments it (workqueue precedent).
        self._ref_cond = threading.Condition(threading.Lock())
        self._refs = 0
        self._acquiring = False
        self._held_since = 0.0

    @property
    def path(self) -> str:
        return self._flock.path

    def acquire(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self._ref_cond:
            while True:
                if self._acquiring:
                    # Someone is mid-acquire on the real flock:
                    # piggyback on their outcome rather than racing a
                    # second syscall.
                    pass
                elif self._refs > 0:
                    if (time.monotonic() - self._held_since
                            < self._max_shared_hold_s):
                        self._refs += 1
                        return
                    # Drain: the shared hold has run long enough; wait
                    # for a full release so another PROCESS gets its
                    # flock handoff window before we re-share.
                else:
                    self._acquiring = True
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._ref_cond.wait(
                        timeout=remaining):
                    raise FlockTimeout(
                        f"shared flock on {self._flock.path} not "
                        f"acquired within {timeout}s")
        try:
            # The blocking flock syscall runs OUTSIDE the condition so
            # joiners park on the condition, not behind a held mutex.
            self._flock.acquire(
                timeout=max(0.05, deadline - time.monotonic()))
        except BaseException:
            with self._ref_cond:
                self._acquiring = False
                self._ref_cond.notify_all()
            raise
        with self._ref_cond:
            self._acquiring = False
            self._refs = 1
            self._held_since = time.monotonic()
            self._ref_cond.notify_all()

    def release(self) -> None:
        with self._ref_cond:
            self._refs -= 1
            if self._refs > 0:
                return
            self._flock.release()
            self._ref_cond.notify_all()
