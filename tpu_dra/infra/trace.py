"""End-to-end claim tracing: spans, trace-context propagation, flight
recorder (SURVEY §19).

Every PR so far re-plumbed its own stopwatch keys through the prepare
pipeline by hand; when a claim wedges, the only evidence is scattered
counters. This module is the observability substrate the p99 gates of
the inference-surge and gang-scheduling scenarios will be measured on:

- **Span** / **Tracer** — a dependency-free span layer: trace_id /
  span_id / parent, monotonic timestamps, attributes, status, a
  context-manager API (``with TRACER.span(...)``) plus an explicit
  ``begin``/``end``/``abandon`` API for spans that cross function or
  thread boundaries, and a thread-local current-span stack. dralint
  R12 enforces the begin/end discipline statically; chaos/drmc assert
  zero open spans dynamically at every quiesce/terminal state.
- **W3C-style trace-context propagation** — ``format_traceparent`` /
  ``parse_traceparent`` carry ``00-<32hex>-<16hex>-01`` strings across
  every process boundary the claim crosses: the scheduler stamps one
  into the claim's ``tpu.dev/traceparent`` annotation at allocation,
  the RPC layer re-stamps its own span before handing the claim to
  ``DeviceState.prepare_batch``, the prepare pipeline exports
  ``TPU_DRA_TRACEPARENT`` into the claim CDI env next to
  ``TPU_CHIP_COORDS``, and ``meshexport.plan_from_env`` / the CD
  daemon's readiness mirror close the loop — one claim, one tree from
  ``sched.pod_seen`` through ``mesh.build``.
- **FlightRecorder** — a bounded lock-free ring of recent spans,
  fault-site firings, and workqueue events, dumped to a JSON file when
  the health-monitor wedged gauge sets, a chaos invariant fires, or
  ``SIGUSR1`` arrives — so a wedged claim ships its evidence instead
  of a shrug.

Ownership and hot-path rules:

- The tracer takes **no locks**: span ids come from a GIL-atomic
  counter, the ring is a ``collections.deque(maxlen=...)`` (appends are
  atomic under the GIL), and open-span tracking is plain dict set/del.
  No new lock classes means no new lock-order edges for draracer's
  observed⊆static gate and no new drmc yield points — tracing never
  changes an interleaving.
- ``set_enabled(False)`` keeps timestamps (the bench breakdowns are
  derived from span durations either way) but skips id generation,
  open-span tracking, and ring emission — the perf tier's tracing
  on/off A/B gates the delta at ≤5%.
- The ``trace.emit`` fault site guards emission only: a firing drops
  the span (counted, trace marked dropped) and never breaks the traced
  operation.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import tempfile
import threading
import time
from collections import deque
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from tpu_dra.infra import faults as _faults
from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry

# The claim annotation the scheduler stamps at allocation and every
# later hop re-stamps with its own span (W3C propagation: each hop
# overwrites the parent id, the trace id is immutable).
TRACEPARENT_ANNOTATION = "tpu.dev/traceparent"

# The claim CDI env key the prepare pipeline exports next to
# TPU_CHIP_COORDS; workload-side consumers (meshexport.plan_from_env,
# the CD daemon readiness mirror) continue the trace from it.
ENV_TRACEPARENT = "TPU_DRA_TRACEPARENT"

# Flight-recorder ring capacity (events, all kinds). Sized so a whole
# chaos walk or a few hundred claim lifecycles fit without eviction;
# eviction is silent by design — the recorder is recent evidence, not
# an archive.
RING_SIZE = int(os.environ.get("TPU_DRA_FLIGHTRECORDER_RING", "16384"))

SPANS_STARTED = DefaultRegistry.counter(
    "tpu_dra_trace_spans_started_total",
    "spans begun by the claim tracer (id'd spans only: with tracing "
    "disabled spans still time but are neither counted nor emitted)")
SPANS_COMPLETED = DefaultRegistry.counter(
    "tpu_dra_trace_spans_completed_total",
    "spans ended or abandoned and offered to the flight recorder, "
    "labeled by status (ok|error|abandoned)")
SPANS_DROPPED = DefaultRegistry.counter(
    "tpu_dra_trace_spans_dropped_total",
    "completed spans dropped at the emission seam (trace.emit fault "
    "fired); the traced operation is never affected, and the span's "
    "trace is marked so completeness checks skip its structure")
FLIGHT_OCCUPANCY = DefaultRegistry.gauge(
    "tpu_dra_flightrecorder_ring_occupancy",
    "events currently held in the flight-recorder ring (spans + fault "
    "firings + workqueue events), observed at snapshot/dump time")
FLIGHT_DUMPS = DefaultRegistry.counter(
    "tpu_dra_flightrecorder_dumps_total",
    "flight-recorder dumps written, labeled by trigger reason "
    "(wedged|pipeline-wedged|chaos-violation|sigusr1|manual)")


# ---------------------------------------------------------------------------
# Trace-context strings (W3C traceparent shape)
# ---------------------------------------------------------------------------

def format_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<32hex trace>-<16hex span>-01``; '' for an id-less span."""
    if not trace_id or not span_id:
        return ""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(text: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) or None. Malformed input returns None
    — a torn annotation starts a fresh trace rather than crashing the
    pipeline that carried it (tracing must never break the operation)."""
    if not text:
        return None
    parts = text.split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


# ---------------------------------------------------------------------------
# Span
# ---------------------------------------------------------------------------

class Span:
    """One timed operation. ``end()``/``abandon()`` are idempotent
    (second close is a no-op) and never raise — closes run in finally
    blocks on crash paths."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ns",
                 "end_ns", "status", "attributes", "thread", "_tracer")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, tracer: "Tracer",
                 attributes: Optional[Dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.status = "open"
        self.attributes = attributes
        self.thread = threading.current_thread().name
        self._tracer = tracer

    # -- timing ---------------------------------------------------------

    @property
    def duration_s(self) -> float:
        """Elapsed seconds; live (now - start) while still open, so
        breakdown derivation can read a phase mid-flight."""
        end = self.end_ns if self.end_ns is not None \
            else time.perf_counter_ns()
        return (end - self.start_ns) / 1e9

    @property
    def duration_ms(self) -> float:
        return self.duration_s * 1e3

    # -- lifecycle ------------------------------------------------------

    def end(self, status: str = "ok") -> None:
        self._tracer._close(self, status)

    def abandon(self, reason: str = "") -> None:
        """Close on an error/crash path: status ``abandoned`` (or
        ``error`` when a reason names the failure). A no-op on an
        already-closed span — crash-path finallys sweep every member
        span, and stamping their reason onto spans that ended cleanly
        would corrupt the very evidence the recorder exists for."""
        if self.end_ns is not None:
            return
        if reason:
            if self.attributes is None:
                self.attributes = {}
            self.attributes.setdefault("error", reason)
            self._tracer._close(self, "error")
        else:
            self._tracer._close(self, "abandoned")

    def set(self, **attributes) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes.update(attributes)

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def to_dict(self) -> Dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_ns": self.start_ns, "end_ns": self.end_ns,
                "status": self.status, "thread": self.thread,
                "attributes": self.attributes or {}}

    def __repr__(self) -> str:  # debugging / dump readability
        return (f"Span({self.name} {self.trace_id[:8]}/{self.span_id} "
                f"<-{self.parent_id or 'root'} {self.status})")


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of (kind, ...) event tuples: ("span", Span),
    ("fault", site, t_ns), ("wq", queue, op, key, t_ns). Lock-free:
    deque(maxlen) appends are GIL-atomic; eviction of the oldest event
    is silent (recent evidence, not an archive). ``enabled`` gates the
    hot-path recording sites (workqueue ops) together with the tracer's
    enable flag."""

    def __init__(self, maxlen: int = RING_SIZE):
        self._ring: deque = deque(maxlen=maxlen)
        self.enabled = True

    # -- producers ------------------------------------------------------

    def record_span(self, span: Span) -> None:
        self._ring.append(("span", span))

    def record_fault(self, site: str) -> None:
        """Installed as the fault registry's fire observer (below): every
        armed firing lands in the ring next to the spans it perturbed."""
        if self.enabled:
            self._ring.append(("fault", site, time.perf_counter_ns()))

    def record_wq(self, queue: str, op: str, key: str) -> None:
        self._ring.append(("wq", queue, op, key, time.perf_counter_ns()))

    # -- consumers ------------------------------------------------------

    def spans(self) -> List[Span]:
        """Completed spans currently in the ring, oldest first."""
        return [ev[1] for ev in list(self._ring) if ev[0] == "span"]

    def snapshot(self) -> List[Dict]:
        TRACER.sync_metrics()
        events = list(self._ring)
        FLIGHT_OCCUPANCY.set(len(events))
        out: List[Dict] = []
        for ev in events:
            if ev[0] == "span":
                out.append({"kind": "span", **ev[1].to_dict()})
            elif ev[0] == "fault":
                out.append({"kind": "fault", "site": ev[1], "t_ns": ev[2]})
            else:
                out.append({"kind": "wq", "queue": ev[1], "op": ev[2],
                            "key": ev[3], "t_ns": ev[4]})
        return out

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> str:
        """Write the ring (plus any still-open spans, so a wedge's
        culprit is IN the dump) to a JSON file; returns the path. Never
        raises into the trigger path — a dump failure is logged into
        the returned path string instead of crashing a health callback."""
        doc = {
            "reason": reason,
            "wall_time": time.time(),
            "perf_counter_ns": time.perf_counter_ns(),
            "open_spans": [s.to_dict() for s in TRACER.open_spans()],
            "events": self.snapshot(),
        }
        if path is None:
            base = os.environ.get("TPU_DRA_FLIGHTRECORDER_DIR",
                                  tempfile.gettempdir())
            path = os.path.join(
                base, f"tpu-dra-flightrec-{os.getpid()}-"
                      f"{next(_ids):x}.json")
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
        except OSError as e:
            return f"<dump failed: {e}>"
        FLIGHT_DUMPS.inc(labels={"reason": reason})
        return path

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------

# One process-wide id mint: GIL-atomic, deterministic-friendly (drmc
# replays see the same sequence), collision-free within a process —
# which is all the in-process collectors ever compare.
_ids = itertools.count(1)


class _Tally:
    """Lock-free monotone counter for the span hot path: ``bump`` is an
    ``itertools.count`` step (GIL-atomic, never loses an increment);
    the cached ``value`` store races only in visibility, never in the
    count. The registered ``tpu_dra_trace_*`` counters take a lock on
    every inc — acquiring one inside ``begin``/``_close`` would hand
    draracer's static lock-order graph a metric-lock edge under every
    span-wrapped region (a spurious cycle with the checkpoint lock), so
    the hot path tallies here and ``sync_span_metrics`` pushes deltas
    into the registry at observation points (recorder snapshot/dump,
    tests, scrape prep)."""

    __slots__ = ("_next", "value")

    def __init__(self):
        self._next = itertools.count(1).__next__
        self.value = 0

    def bump(self) -> None:
        self.value = self._next()


class Tracer:
    def __init__(self, recorder: FlightRecorder):
        self._recorder = recorder
        self._enabled = True
        self._tally_started = _Tally()
        self._tally_completed = {"ok": _Tally(), "error": _Tally(),
                                 "abandoned": _Tally()}
        self._tally_dropped = _Tally()
        self._synced: Dict[str, int] = {}
        self._sync_lock = threading.Lock()
        # span_id -> Span for every id'd span begun and not yet closed.
        # Plain dict set/del (GIL-atomic); chaos/drmc assert it drains.
        self._open: Dict[str, Span] = {}
        # trace ids with at least one span lost at the emission seam
        # (trace.emit fault): completeness checks skip tree structure
        # for these but still demand zero open spans.
        self._dropped: set = set()
        self._tls = threading.local()

    # -- enable / disable ----------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        """The perf A/B switch: disabled spans still carry timestamps
        (breakdowns keep working) but mint no ids, are not tracked as
        open, and never reach the recorder."""
        self._enabled = bool(on)
        self._recorder.enabled = bool(on)

    # -- begin / end ----------------------------------------------------

    def begin(self, name: str, *, parent: Optional[Span] = None,
              traceparent: Optional[str] = None,
              attributes: Optional[Dict] = None,
              root: bool = False) -> Span:
        """Open a span. Parent resolution, first match wins: explicit
        `parent` span -> `traceparent` string (malformed ⇒ fresh trace)
        -> the thread-local current span (unless `root`) -> fresh
        trace. Every ``begin`` outside a ``with`` must be paired with
        ``end()``/``abandon()`` on all paths — dralint R12."""
        if not self._enabled:
            return Span(name, "", "", "", self, attributes)
        trace_id = parent_id = ""
        if parent is not None and parent.trace_id:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed is not None:
                trace_id, parent_id = parsed
        if not trace_id and not root:
            cur = self.current()
            if cur is not None and cur.trace_id:
                trace_id, parent_id = cur.trace_id, cur.span_id
        if not trace_id:
            trace_id = f"{next(_ids):032x}"
        span = Span(name, trace_id, f"{next(_ids):016x}", parent_id,
                    self, attributes)
        self._open[span.span_id] = span
        self._tally_started.bump()
        return span

    def end(self, span: Span, status: str = "ok") -> None:
        self._close(span, status)

    def abandon(self, span: Span, reason: str = "") -> None:
        span.abandon(reason)

    def _close(self, span: Span, status: str) -> None:
        if span.end_ns is not None:
            return  # idempotent: crash-path finallys may double-close
        span.end_ns = time.perf_counter_ns()
        span.status = status
        if not span.span_id:
            return  # disabled at begin: timed but never emitted
        self._open.pop(span.span_id, None)
        (self._tally_completed.get(status)
         or self._tally_completed["ok"]).bump()
        # Injection site: emission fails (a real exporter's queue full /
        # serialization error). The span drops, counted, the trace is
        # marked so completeness checks skip its structure — and the
        # traced operation NEVER sees the failure.
        if FAULTS.fires("trace.emit"):
            self._tally_dropped.bump()
            self._dropped.add(span.trace_id)
            if len(self._dropped) > 65536:  # unbounded-growth backstop
                self._dropped.clear()
            return
        self._recorder.record_span(span)

    def sync_metrics(self) -> None:
        """Push the lock-free tallies into the registered counters (see
        _Tally): called at every recorder snapshot/dump and by anything
        about to read the ``tpu_dra_trace_*`` series."""
        pairs = [("started", None, SPANS_STARTED, self._tally_started),
                 ("dropped", None, SPANS_DROPPED, self._tally_dropped)]
        for status, tally in sorted(self._tally_completed.items()):
            pairs.append((f"completed.{status}", {"status": status},
                          SPANS_COMPLETED, tally))
        with self._sync_lock:
            for key, labels, metric, tally in pairs:
                delta = tally.value - self._synced.get(key, 0)
                if delta > 0:
                    metric.inc(delta, labels=labels)
                    self._synced[key] = self._synced.get(key, 0) + delta

    def record_span(self, name: str, duration_s: float, *,
                    parent: Optional[Span] = None,
                    traceparent: Optional[str] = None,
                    attributes: Optional[Dict] = None) -> Span:
        """Synthesize an already-completed span from an externally
        measured duration (e.g. the gRPC handler's decode/encode
        stopwatches, a journal segment shared by a whole batch): start
        is backdated so [start, end] covers the measured window."""
        span = self.begin(name, parent=parent, traceparent=traceparent,
                          attributes=attributes, root=parent is None
                          and traceparent is None)
        self.end(span)
        # Backdate AFTER the close so [start, end] is exactly the
        # measured window (the begin->end gap would otherwise pad it).
        span.start_ns = span.end_ns - int(duration_s * 1e9)
        return span

    # -- context-manager API + thread-local stack -----------------------

    def span(self, name: str, *, parent: Optional[Span] = None,
             traceparent: Optional[str] = None,
             attributes: Optional[Dict] = None, root: bool = False):
        """``with TRACER.span("x") as s:`` — begins, pushes onto this
        thread's current-span stack (nested ``begin``s with no explicit
        parent attach here), ends ``ok`` on normal exit and ``error``
        on exception."""
        return _SpanContext(self, name, parent, traceparent, attributes,
                            root)

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _push(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()

    # -- introspection (chaos / drmc / tests) ---------------------------

    def open_spans(self) -> List[Span]:
        return list(self._open.values())

    def open_ids(self) -> FrozenSet[str]:
        """Snapshot of open span ids — harnesses take one at build time
        and assert only NEW spans drained (cross-test leakage of a
        sibling harness must not fail this one)."""
        return frozenset(self._open)

    def open_since(self, snapshot: FrozenSet[str]) -> List[Span]:
        return [s for sid, s in list(self._open.items())
                if sid not in snapshot]

    def trace_spans(self, trace_id: str) -> List[Span]:
        """Completed spans of one trace still in the recorder ring,
        start-ordered, plus any still-open spans of the trace."""
        spans = [s for s in self._recorder.spans()
                 if s.trace_id == trace_id]
        spans += [s for s in self._open.values()
                  if s.trace_id == trace_id]
        return sorted(spans, key=lambda s: s.start_ns)

    def trace_dropped(self, trace_id: str) -> bool:
        return trace_id in self._dropped


class _SpanContext:
    def __init__(self, tracer: Tracer, name: str, parent, traceparent,
                 attributes, root):
        self._tracer = tracer
        self._args = (name, parent, traceparent, attributes, root)
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        name, parent, traceparent, attributes, root = self._args
        self._span = self._tracer.begin(
            name, parent=parent, traceparent=traceparent,
            attributes=attributes, root=root)
        self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, _tb) -> None:
        span = self._span
        self._tracer._pop(span)
        if exc_type is None:
            span.end()
        else:
            span.abandon(f"{exc_type.__name__}: {exc}")


# ---------------------------------------------------------------------------
# Trace-completeness verification (chaos quiesce, drmc terminal states,
# the e2e structural assertion)
# ---------------------------------------------------------------------------

def verify_trace(trace_id: str, tracer: Optional[Tracer] = None
                 ) -> List[str]:
    """Violations of one trace's completeness contract:

    - **no open spans** — every span of the trace is closed;
    - **parents precede children** — every referenced parent is present
      (spans cross process boundaries conceptually, so containment is
      not required — a scheduler span legitimately ends before the RPC
      span it parents begins) and starts no later than its child;
    - **prepare spans nest under the RPC span** — when the trace has an
      ``rpc.*`` span, every ``prepare.*`` span's ancestry reaches one.

    A trace marked dropped (trace.emit fault fired on one of its spans)
    skips the structural checks — the open-span demand still holds.
    """
    tracer = tracer or TRACER
    spans = tracer.trace_spans(trace_id)
    out: List[str] = []
    if not spans:
        if tracer.trace_dropped(trace_id):
            return out  # EVERY span lost at the emit seam: structure
            # unknowable, and nothing is open — complete by decree.
        return [f"trace {trace_id}: no spans recorded"]
    for s in spans:
        if s.end_ns is None:
            out.append(f"trace {trace_id}: span {s.name} still open")
    if tracer.trace_dropped(trace_id):
        return out  # structure unknowable: a span was dropped at emit
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        if not s.parent_id:
            continue
        parent = by_id.get(s.parent_id)
        if parent is None:
            out.append(f"trace {trace_id}: span {s.name} references "
                       f"missing parent {s.parent_id}")
        elif parent.start_ns > s.start_ns:
            out.append(f"trace {trace_id}: parent {parent.name} starts "
                       f"after child {s.name}")
    rpc_ids = {s.span_id for s in spans if s.name.startswith("rpc.")}
    if rpc_ids:
        for s in spans:
            if not s.name.startswith("prepare."):
                continue
            cur, hops = s, 0
            while cur is not None and hops < len(spans) + 1:
                if cur.span_id in rpc_ids:
                    break
                cur = by_id.get(cur.parent_id)
                hops += 1
            else:
                cur = None
            if cur is None:
                out.append(f"trace {trace_id}: prepare span {s.name} "
                           "does not nest under any rpc.* span")
    return out


def span_tree(trace_id: str, tracer: Optional[Tracer] = None
              ) -> Dict[str, List[Span]]:
    """parent span name -> child spans (start-ordered), '' for roots —
    the shape the e2e structural assertion walks."""
    tracer = tracer or TRACER
    out: Dict[str, List[Span]] = {}
    spans = tracer.trace_spans(trace_id)
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        parent = by_id.get(s.parent_id)
        out.setdefault(parent.name if parent else "", []).append(s)
    return out


# ---------------------------------------------------------------------------
# Module singletons + trigger wiring
# ---------------------------------------------------------------------------

RECORDER = FlightRecorder()
TRACER = Tracer(RECORDER)

# Fault firings land in the ring next to the spans they perturbed; the
# hook keeps infra/faults.py dependency-free (no import cycle).
_faults.set_fire_observer(RECORDER.record_fault)


# reason -> monotonic ns of its last dump (the rate-limit ledger for
# triggers that can fire in storms). GIL-atomic dict ops; a racing pair
# of dumps at the window edge is harmless (two files, not thousands).
_last_dump_ns: Dict[str, int] = {}


def dump_flight_recorder(reason: str, path: Optional[str] = None,
                         min_interval_s: float = 0.0) -> str:
    """The one dump entry point every trigger uses: the health monitor's
    wedged branch, the RPC pipeline's wedged-window timeout, chaos's
    any-violation export, SIGUSR1, operators.

    `min_interval_s` rate-limits storm-prone triggers: a wedged
    pipeline fails every retrying RPC for its full timeout, and each
    failure dumping a multi-MB ring would fill the wedged node's tmp
    with identical evidence. Within the window the previous dump is the
    evidence — return a marker instead of a new file."""
    if min_interval_s > 0:
        now = time.monotonic_ns()
        last = _last_dump_ns.get(reason)
        if last is not None and now - last < min_interval_s * 1e9:
            return f"<rate-limited: last {reason} dump " \
                   f"{(now - last) / 1e9:.1f}s ago>"
        _last_dump_ns[reason] = now
    return RECORDER.dump(reason=reason, path=path)


def open_span_violations(snapshot: FrozenSet[str],
                         context: str = "at quiesce") -> List[str]:
    """The zero-open-span invariant, formatted once for every consumer
    (chaos harness quiesce, drmc terminal states): spans begun after
    `snapshot` (``Tracer.open_ids()``) that are still open."""
    return [f"span left open {context}: {s.name} (trace {s.trace_id})"
            for s in TRACER.open_since(snapshot)]


def install_signal_handler(signum: int = signal.SIGUSR1) -> bool:
    """SIGUSR1 -> flight-recorder dump (the 'what is this process doing
    RIGHT NOW' lever for a wedged pod). Main-thread only — returns
    False (no-op) elsewhere so library embedding never crashes."""
    def _handler(_sig, _frame):
        path = dump_flight_recorder("sigusr1")
        print(f"flight recorder dumped to {path}", flush=True)

    try:
        signal.signal(signum, _handler)
    except ValueError:  # not the main thread
        return False
    return True
