"""Active-standby leader election over a coordination.k8s.io Lease.

The HA half of ROADMAP item 3 (SURVEY §22): the scheduler becomes a
replicated control-plane component by putting every replica behind an
elector. Exactly one replica acts at a time; the others run warm
informers with a paused workqueue and take over when the lease expires.

Three pieces, mirroring client-go's leaderelection package shrunk to
what the sim needs:

- **LeaderElector** — a jittered renew loop per replica. The holder
  renews ``spec.renewTime`` by CAS (the fake apiserver's
  resourceVersion conflict is the compare half); a standby watches for
  expiry and CASes itself in, bumping ``spec.leaseTransitions``. Two
  standbys racing a takeover CAS the same resourceVersion and exactly
  one wins — the double-takeover race is settled by the apiserver, not
  by client-side luck.

- **Fencing** — ``leaseTransitions`` is the fencing generation. A
  leader stamps its current generation into every claim-status write
  (scheduler._stamp_fence); ``install_fencing`` adds an apiserver-side
  reactor that refuses any stamped write whose generation is behind
  the lease's. A deposed leader that missed its own deposal (GC pause,
  partition) keeps stamping the OLD generation, so its late commits
  are refused — never silently landed next to the new leader's. The
  elector deliberately never clears the generation on step-down:
  fencing only works if the stale stamp keeps flowing. Fencing is
  scoped to ResourceClaims: the scheduler is their only round-trip
  writer (and always re-stamps with its current generation), so a
  stale stamp can never poison a fencing-unaware path — unlike pods,
  which nodesim co-writes and which are therefore neither stamped nor
  fenced.

- **Step-down** — a leader whose renew keeps failing past the lease
  duration stops acting (the ``sched.lease_renew`` site's declared
  degradation). Correctness never depends on it (fencing refuses the
  writes regardless); it just stops burning work on a lost lease.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional

from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.metrics import SCHED_LEADER, SCHED_LEASE_TRANSITIONS
from tpu_dra.k8s.client import (
    AlreadyExistsError, ApiClient, ApiError, ConflictError, NotFoundError,
    json_deepcopy,
)
from tpu_dra.k8s.fake import new_lease, lease_micro_time, \
    parse_lease_micro_time
from tpu_dra.k8s.resources import LEASES, RESOURCECLAIMS

log = logging.getLogger("tpu_dra.leaderelect")

LEASE_NAME = "sim-scheduler"
LEASE_NAMESPACE = "kube-system"

# Stamped into every acting leader's claim-status writes; compared by
# the install_fencing reactor against the lease's current
# leaseTransitions.
FENCING_ANNOTATION = "sim/sched-lease-generation"


class LeaderElector:
    """One replica's election loop. Callbacks run on the elector
    thread: ``on_started_leading(generation)`` at acquire/takeover,
    ``on_stopped_leading(reason)`` at step-down or observed deposal.
    They must be quick or hand off (the scheduler's promote() rebuilds
    the index inline — acceptable: a takeover IS the failover path)."""

    def __init__(self, client: ApiClient, identity: str, *,
                 name: str = LEASE_NAME,
                 namespace: str = LEASE_NAMESPACE,
                 lease_duration_s: float = 1.0,
                 renew_interval_s: float = 0.25,
                 jitter: float = 0.2,
                 on_started_leading: Optional[Callable[[int], None]] = None,
                 on_stopped_leading: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.time,
                 seed: Optional[int] = None):
        self._client = client
        self.identity = identity
        self._name = name
        self._namespace = namespace
        self._lease_duration_s = lease_duration_s
        self._renew_interval_s = renew_interval_s
        self._jitter = jitter
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._clock = clock
        self._rng = random.Random(seed if seed is not None
                                  else hash(identity) & 0xFFFFFFFF)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.is_leader = False
        # The fencing token of the LAST successful acquire — kept
        # through step-down (see module docstring).
        self.generation: Optional[int] = None
        self._last_renew = 0.0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"leaderelect-{self.identity}")
        self._thread.start()

    def stop(self, release: bool = False) -> None:
        """Stop electing. ``release=True`` models graceful handover:
        zero out renewTime so a standby takes over without waiting out
        the duration; default (False) is the crash/kill shape the
        chaos matrix drives — the standby must detect expiry."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        was_leader = self.is_leader
        if self.is_leader:
            self._step_down("stopped")
        if release and was_leader:
            try:
                lease = self._client.get(LEASES, self._name,
                                         self._namespace)
                spec = lease.get("spec") or {}
                if spec.get("holderIdentity") == self.identity:
                    upd = json_deepcopy(lease)
                    upd["spec"]["renewTime"] = lease_micro_time(0.0)
                    self._client.update(LEASES, upd, self._namespace)
            except ApiError:
                pass  # drflow: swallow-ok[best-effort handover: the
            #   lease simply expires on schedule instead]

    def tick(self) -> None:
        """One election step (public for deterministic tests/drmc —
        the run loop is exactly this under a jittered timer)."""
        self._tick()

    # -- the loop ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("election tick failed (%s)", self.identity)
            self._stop.wait(self._renew_interval_s
                            * (1.0 + self._jitter * self._rng.random()))

    def _tick(self) -> None:
        now = self._clock()
        try:
            lease = self._client.get(LEASES, self._name, self._namespace)
        except NotFoundError:
            self._create(now)
            return
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        if holder == self.identity:
            self._renew(lease, now)
            return
        if self.is_leader:
            # Someone else took the lease while we thought we held it
            # (our renew lost the CAS race): we are deposed. Fencing
            # already refuses our late writes; stop acting too.
            self._step_down(f"deposed by {holder}")
        duration = float(spec.get("leaseDurationSeconds")
                         or self._lease_duration_s)
        renewed = parse_lease_micro_time(spec.get("renewTime"))
        if now - renewed < duration:
            return  # live foreign leader: stay standby
        self._takeover(lease, now)

    def _create(self, now: float) -> None:
        obj = new_lease(self._name, self._namespace, self.identity,
                        self._lease_duration_s, now)
        try:
            created = self._client.create(LEASES, obj, self._namespace)
        except AlreadyExistsError:
            return  # raced another replica's create: it leads
        self._became_leader(created, now)

    def _renew(self, lease, now: float) -> None:
        try:
            # Injection site: the renew write fails (apiserver blip) or
            # the CAS loses to a racing takeover.
            FAULTS.check("sched.lease_renew", identity=self.identity)
            upd = json_deepcopy(lease)
            upd["spec"]["renewTime"] = lease_micro_time(now)
            self._client.update(LEASES, upd, self._namespace)
            self._last_renew = now
            if not self.is_leader:
                # Holder per the lease but not acting (e.g. restarted
                # replica finding its own still-live lease): resume.
                self._became_leader(upd, now)
        except (FaultInjected, ConflictError, NotFoundError) as e:
            # Declared degradation (sched.lease_renew): renews failing
            # past the lease duration step the leader down — its lease
            # is as good as lost and fencing is already refusing its
            # commits.
            if self.is_leader and \
                    now - self._last_renew >= self._lease_duration_s:
                self._step_down(f"renew failing past lease duration: {e}")

    def _takeover(self, lease, now: float) -> None:
        upd = json_deepcopy(lease)
        spec = upd.setdefault("spec", {})
        spec["holderIdentity"] = self.identity
        spec["acquireTime"] = spec["renewTime"] = lease_micro_time(now)
        spec["leaseDurationSeconds"] = self._lease_duration_s
        spec["leaseTransitions"] = int(spec.get("leaseTransitions") or 0) + 1
        try:
            updated = self._client.update(LEASES, upd, self._namespace)
        except (ConflictError, NotFoundError):
            return  # lost the takeover CAS: exactly one standby wins
        self._became_leader(updated, now)

    # -- transitions ---------------------------------------------------------

    def _became_leader(self, lease, now: float) -> None:
        generation = int((lease.get("spec") or {})
                         .get("leaseTransitions") or 0)
        with self._lock:
            self.is_leader = True
            self.generation = generation
            self._last_renew = now
        SCHED_LEASE_TRANSITIONS.inc()
        SCHED_LEADER.set(1, labels={"identity": self.identity})
        log.info("%s acquired scheduler lease (generation %d)",
                 self.identity, generation)
        if self._on_started:
            self._on_started(generation)

    def _step_down(self, reason: str) -> None:
        with self._lock:
            if not self.is_leader:
                return
            self.is_leader = False
            # self.generation intentionally KEPT: the stale stamp is
            # what fencing refuses.
        SCHED_LEADER.set(0, labels={"identity": self.identity})
        log.warning("%s stepped down: %s", self.identity, reason)
        if self._on_stopped:
            self._on_stopped(reason)


def install_fencing(cluster, *, name: str = LEASE_NAME,
                    namespace: str = LEASE_NAMESPACE):
    """Apiserver-side fencing (FakeCluster reactor): refuse any
    ResourceClaim update stamped with a lease generation BEHIND the
    lease's current leaseTransitions — the deposed leader's late
    commit, arriving after a takeover bumped the generation. Scoped to
    claims (the scheduler's commit objects, which it always re-stamps);
    writes without the stamp pass, and a missing lease passes (no
    election in this cluster). Returns the reactor so tests can
    remove it."""

    def _fence(verb: str, gvr, obj):
        if verb != "update" or obj is None \
                or gvr.key != RESOURCECLAIMS.key:
            return None
        stamped = ((obj.get("metadata") or {}).get("annotations")
                   or {}).get(FENCING_ANNOTATION)
        if stamped is None:
            return None
        try:
            lease = cluster.get(LEASES, name, namespace)
        except NotFoundError:
            return None
        current = int((lease.get("spec") or {})
                      .get("leaseTransitions") or 0)
        if int(stamped) < current:
            raise ConflictError(
                f"{gvr.plural}/{(obj.get('metadata') or {}).get('name')}: "
                f"fenced write refused (lease generation {stamped} < "
                f"current {current})")
        return None

    cluster.reactors.append(_fence)
    return _fence
