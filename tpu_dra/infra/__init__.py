"""L5 shared infrastructure (reference: pkg/ + internal/common)."""
