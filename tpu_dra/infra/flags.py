"""Env-backed CLI flag system + logging configuration.

Reference: pkg/flags (kubeclient.go:33-147, logging.go, featuregates.go:212-275)
and the urfave/cli pattern of cmd/*/main.go:82-160 where every flag has an
env-var mirror (12-factor: Helm values -> container env -> flags). We build
on argparse; each Flag declares its env mirror and the parsed config can be
dumped at startup (LogStartupConfig analog).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from tpu_dra.infra import featuregates


@dataclass
class Flag:
    name: str                 # e.g. "node-name"
    env: str                  # e.g. "NODE_NAME"
    default: Any = None
    type: Callable = str
    help: str = ""
    required: bool = False

    @property
    def attr(self) -> str:
        return self.name.replace("-", "_")


class FlagSet:
    def __init__(self, prog: str, flags: List[Flag]):
        self._flags = flags
        self._parser = argparse.ArgumentParser(prog=prog)
        for f in flags:
            env_val = os.environ.get(f.env)
            default = f.default
            if env_val is not None:
                default = self._coerce(f, env_val)
            # argparse's type=bool would turn any non-empty string (including
            # "false") into True; route bools through the same str coercion
            # the env mirror uses.
            argtype = (lambda raw, _f=f: self._coerce(_f, raw)) if f.type is bool else f.type
            self._parser.add_argument(
                f"--{f.name}", dest=f.attr, default=default, type=argtype,
                help=f"{f.help} [env: {f.env}]")

    @staticmethod
    def _coerce(f: Flag, raw: str) -> Any:
        if f.type is bool:
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return f.type(raw)

    def parse(self, argv: Optional[List[str]] = None) -> argparse.Namespace:
        ns = self._parser.parse_args(argv)
        for f in self._flags:
            if f.required and getattr(ns, f.attr) in (None, ""):
                self._parser.error(
                    f"--{f.name} (or env {f.env}) is required")
        return ns

    def dump_config(self, ns: argparse.Namespace, log: logging.Logger) -> None:
        """Startup-config dump (pkg/flags LogStartupConfig analog)."""
        cfg = {f.name: getattr(ns, f.attr) for f in self._flags}
        cfg["feature-gates"] = featuregates.Features.as_string()
        log.info("startup configuration: %s", json.dumps(cfg, default=str, sort_keys=True))


def feature_gate_flag() -> Flag:
    return Flag(name="feature-gates", env="FEATURE_GATES", default="",
                help="comma-separated Name=true|false feature gate assignments")


def apply_feature_gates(ns: argparse.Namespace) -> None:
    raw = getattr(ns, "feature_gates", "")
    if raw:
        featuregates.Features.set_from_string(raw)


_JSON_LOGGING = False


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {"ts": self.formatTime(record), "level": record.levelname.lower(),
               "logger": record.name, "msg": record.getMessage()}
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def setup_logging(verbosity: int = 0, json_format: bool = False) -> logging.Logger:
    """klog-style: -v levels map to logging levels; optional JSON output
    (pkg/flags/logging.go supports a JSON logging config)."""
    level = logging.DEBUG if verbosity >= 4 else logging.INFO
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s] %(message)s"))
    root = logging.getLogger("tpu_dra")
    root.handlers[:] = [handler]
    root.setLevel(level)
    return root


def logging_flags() -> List[Flag]:
    return [
        Flag(name="v", env="LOG_VERBOSITY", default=0, type=int,
             help="log verbosity (klog-style numeric level)"),
        Flag(name="log-json", env="LOG_JSON", default=False, type=bool,
             help="emit JSON-formatted logs"),
    ]
