"""VFIO passthrough manager: rebind a TPU chip's PCI function from the
accel driver to vfio-pci so a VM workload can claim the whole device.

Reference mechanics (semantic port, not line port):
- cmd/gpu-kubelet-plugin/vfio-device.go:33-264 — Prechecks (module +
  IOMMU), Configure/Unconfigure with per-device locks, device-busy wait
  (`fuser`), driver readlink dispatch.
- scripts/bind_to_driver.sh:6-37 — driver_override write then bind-file
  write, rolling the override back on bind failure.
- scripts/unbind_from_driver.sh — unbind via the bound driver's own
  unbind file, tolerating an already-unbound device.

TPU differences:
- the busy check scans /proc/*/fd for the chip's /dev/accelN (no `fuser`
  binary dependency, works in a slim container),
- sibling PCI functions in the same IOMMU group are rebound as a unit —
  the kernel refuses the vfio fd otherwise (reference handles siblings in
  device_state.go:526-552),
- everything runs against an injectable filesystem root so the whole flow
  is testable on the fake sysfs tree (tpu_dra/native/tpuinfo.py
  make_fake_sysfs), the design improvement SURVEY §7.3 calls for.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.native.tpuinfo import Chip

log = logging.getLogger(__name__)

VFIO_DRIVER = "vfio-pci"
# Driver name the accel chips are normally bound to (the `nvidia` analog).
TPU_DRIVER = "tpu-accel"


class PassthroughError(Exception):
    pass


class PciSysfs:
    """Raw sysfs/dev/proc operations against an injectable root.

    All paths are the kernel ABI ones; `root` prefixes them so tests (and
    kind-style CI nodes) can point at a materialized fake tree.
    """

    def __init__(self, root: str = "/"):
        self.root = root.rstrip("/")

    def _p(self, *parts: str) -> str:
        return os.path.join(self.root + "/", *parts)

    # -- module / IOMMU prechecks ------------------------------------------

    def module_loaded(self, module: str) -> bool:
        return os.path.isdir(self._p("sys", "module", module))

    def iommu_enabled(self) -> bool:
        path = self._p("sys", "kernel", "iommu_groups")
        try:
            return bool(os.listdir(path))
        except FileNotFoundError:
            return False

    # -- device state -------------------------------------------------------

    def current_driver(self, pci_address: str) -> Optional[str]:
        link = self._p("sys", "bus", "pci", "devices", pci_address, "driver")
        try:
            return os.path.basename(os.readlink(link))
        except OSError:
            return None

    def iommu_group(self, pci_address: str) -> Optional[str]:
        link = self._p("sys", "bus", "pci", "devices", pci_address,
                       "iommu_group")
        try:
            return os.path.basename(os.readlink(link))
        except OSError:
            return None

    def group_devices(self, group: str) -> List[str]:
        path = self._p("sys", "kernel", "iommu_groups", group, "devices")
        try:
            return sorted(os.listdir(path))
        except FileNotFoundError:
            return []

    # -- rebind primitives (bind_to_driver.sh semantics) --------------------

    def write_driver_override(self, pci_address: str, driver: str) -> None:
        path = self._p("sys", "bus", "pci", "devices", pci_address,
                       "driver_override")
        if not os.path.exists(path):
            raise PassthroughError(f"{path} does not exist")
        with open(path, "w") as f:
            f.write(driver + "\n" if driver else "\n")

    def unbind(self, pci_address: str) -> None:
        """Write the address to the bound driver's unbind file; no-op when
        already unbound (unbind_from_driver.sh behavior)."""
        drv = self.current_driver(pci_address)
        if drv is None:
            return
        path = self._p("sys", "bus", "pci", "devices", pci_address,
                       "driver", "unbind")
        with open(path, "w") as f:
            f.write(pci_address)

    def bind(self, pci_address: str, driver: str) -> None:
        path = self._p("sys", "bus", "pci", "drivers", driver, "bind")
        if not os.path.exists(path):
            raise PassthroughError(
                f"driver {driver!r} has no bind file at {path}")
        with open(path, "w") as f:
            f.write(pci_address)

    # -- busy check (fuser analog) ------------------------------------------

    def open_fds_for(self, dev_path: str) -> List[int]:
        """Pids holding an open fd on dev_path, via /proc scan."""
        target = self._p(dev_path.lstrip("/"))
        pids: List[int] = []
        proc = self._p("proc")
        try:
            entries = os.listdir(proc)
        except FileNotFoundError:
            return []
        for pid in entries:
            if not pid.isdigit():
                continue
            fd_dir = os.path.join(proc, pid, "fd")
            try:
                fds = os.listdir(fd_dir)
            except OSError:
                continue
            for fd in fds:
                try:
                    if os.readlink(os.path.join(fd_dir, fd)) in (
                            dev_path, target):
                        pids.append(int(pid))
                        break
                except OSError:
                    continue
        return pids


class PassthroughManager:
    """Configure/Unconfigure chips for VFIO passthrough
    (VfioPciManager analog, vfio-device.go:33-264)."""

    # The busy-wait runs inside DeviceState.prepare's lock, exactly like
    # the reference (WaitForGPUFree under the DeviceState mutex,
    # vfio-device.go:132-157 with gpuFreeCheckTimeout=60s) — but we cap it
    # at 30s so a stuck passthrough prepare cannot starve unrelated
    # prepare/unprepare calls past kubelet's retry window.
    def __init__(self, sysfs: Optional[PciSysfs] = None, *,
                 tpu_driver: str = TPU_DRIVER,
                 free_timeout: float = 30.0, free_interval: float = 1.0,
                 bind_timeout: float = 5.0):
        self._fs = sysfs or PciSysfs()
        self._tpu_driver = tpu_driver
        self._free_timeout = free_timeout
        self._free_interval = free_interval
        self._bind_timeout = bind_timeout
        # Per-chip mutexes (mutex.go:22-43 perGpuLock analog).
        self._locks: Dict[str, threading.Lock] = {}
        self._locks_mu = threading.Lock()

    def _lock_for(self, key: str) -> threading.Lock:
        with self._locks_mu:
            return self._locks.setdefault(key, threading.Lock())

    # -- prechecks (vfio-device.go:76-88) -----------------------------------

    def prechecks(self) -> None:
        if not self._fs.module_loaded("vfio_pci"):
            raise PassthroughError("vfio_pci module is not loaded")
        if not self._fs.iommu_enabled():
            raise PassthroughError("IOMMU is not enabled in the kernel")

    # -- group topology (for DeviceState's exclusivity guard) ---------------

    def group_of(self, chip: Chip) -> Optional[str]:
        return (self._fs.iommu_group(chip.pci_address)
                if chip.pci_address else None)

    def group_devices(self, group: str) -> List[str]:
        return self._fs.group_devices(group)

    # -- configure ----------------------------------------------------------

    def configure(self, chip: Chip,
                  sibling_dev_paths: Optional[Dict[str, str]] = None) -> str:
        """Bind the chip (and its IOMMU-group siblings) to vfio-pci.
        Returns the IOMMU group id whose /dev/vfio/<group> node the CDI
        spec must inject. Idempotent.

        The caller (DeviceState) is responsible for asserting that no
        other claim holds any chip in the group — this method will yank
        siblings, which is only safe under that exclusivity.
        sibling_dev_paths maps sibling PCI addresses to their /dev/accelN
        paths so the busy-wait covers every accel function rebound."""
        if not chip.pci_address:
            raise PassthroughError(
                f"chip {chip.index} has no PCI address; cannot passthrough")
        with self._lock_for(chip.pci_address):
            self.prechecks()
            group = self._fs.iommu_group(chip.pci_address)
            if group is None:
                raise PassthroughError(
                    f"chip {chip.index} ({chip.pci_address}) has no IOMMU "
                    "group")
            # Every function in the group must leave the host driver or the
            # kernel refuses the vfio fd.
            sib = sibling_dev_paths or {}
            for addr in self._fs.group_devices(group) or [chip.pci_address]:
                busy = (chip.dev_path if addr == chip.pci_address
                        else sib.get(addr))
                self._rebind(addr, VFIO_DRIVER, busy_dev=busy)
            return group

    def unconfigure(self, chip: Chip) -> None:
        """Return the chip's group to the accel driver. Idempotent."""
        if not chip.pci_address:
            return
        with self._lock_for(chip.pci_address):
            group = self._fs.iommu_group(chip.pci_address)
            for addr in (self._fs.group_devices(group)
                         if group else [chip.pci_address]):
                self._rebind(addr, self._tpu_driver, busy_dev=None)

    def cdi_device_nodes(self, group: str) -> List[Dict]:
        """CDI deviceNodes edit for a configured group
        (GetVfioCommonCDIContainerEdits analog)."""
        return [{"path": "/dev/vfio/vfio"},
                {"path": f"/dev/vfio/{group}"}]

    # -- internals ----------------------------------------------------------

    def _rebind(self, pci_address: str, target_driver: str,
                busy_dev: Optional[str]) -> None:
        current = self._fs.current_driver(pci_address)
        if current == target_driver:
            return
        # Dispatch on the current driver like Configure does
        # (vfio-device.go:173-186): only rebinds between the accel driver
        # and vfio-pci are supported; anything else is operator error.
        if current is not None and current not in (self._tpu_driver,
                                                   VFIO_DRIVER):
            raise PassthroughError(
                f"{pci_address} is bound to {current!r}, expected "
                f"{self._tpu_driver!r} or {VFIO_DRIVER!r}")
        if busy_dev is not None:
            self._wait_device_free(pci_address, busy_dev)
        self._fs.write_driver_override(pci_address, target_driver)
        try:
            self._fs.unbind(pci_address)
            self._fs.bind(pci_address, target_driver)
            self._wait_bound(pci_address, target_driver)
        except Exception:
            # bind_to_driver.sh rolls the override back on failure so the
            # device can rebind normally later.
            try:
                self._fs.write_driver_override(pci_address, "")
            except Exception:  # noqa: BLE001
                log.warning("override rollback failed for %s", pci_address)
            raise
        # Success: clear the override so future hotplug events bind
        # normally; the explicit bind already happened.
        self._fs.write_driver_override(pci_address, "")
        log.info("rebound %s -> %s", pci_address, target_driver)

    def _wait_device_free(self, pci_address: str, dev_path: str) -> None:
        """WaitForGPUFree analog (vfio-device.go:132-157): poll until no
        process holds the device node open."""
        deadline = time.monotonic() + self._free_timeout
        while True:
            pids = self._fs.open_fds_for(dev_path)
            if not pids:
                return
            if time.monotonic() >= deadline:
                raise PassthroughError(
                    f"timed out waiting for {dev_path} ({pci_address}) to "
                    f"be free; held by pids {pids}")
            log.info("%s busy (pids %s); waiting", dev_path, pids)
            time.sleep(self._free_interval)

    def _wait_bound(self, pci_address: str, driver: str) -> None:
        deadline = time.monotonic() + self._bind_timeout
        while time.monotonic() < deadline:
            if self._fs.current_driver(pci_address) == driver:
                return
            time.sleep(0.02)
        raise PassthroughError(
            f"{pci_address} did not bind to {driver} within "
            f"{self._bind_timeout}s (bound: "
            f"{self._fs.current_driver(pci_address)!r})")
