"""TPU kubelet plugin entrypoint.

Reference: cmd/gpu-kubelet-plugin/main.go:44-293 — env-mirrored flags,
debug signal handlers, driver construction, serve until signalled.

Run: ``python -m tpu_dra.tpuplugin.main [flags]``
"""

from __future__ import annotations

import os
import signal
import threading

from tpu_dra.api.types import TPU_DRIVER_NAME
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.infra import debug, featuregates, trace
from tpu_dra.infra.flags import (
    Flag, FlagSet, apply_feature_gates, feature_gate_flag, logging_flags,
    setup_logging,
)
from tpu_dra.infra.metrics import MetricsServer
from tpu_dra.k8s.client import HttpApiClient, RetryingApiClient
from tpu_dra.native.tpuinfo import get_backend
from tpu_dra.tpuplugin.checkpoint import CheckpointManager
from tpu_dra.tpuplugin.device_state import DeviceState
from tpu_dra.tpuplugin.driver import TpuDriver
from tpu_dra.tpuplugin.sharing import MultiprocessManager, TimeSlicingManager


def flags() -> FlagSet:
    return FlagSet("tpu-kubelet-plugin", [
        Flag("node-name", "NODE_NAME", required=True,
             help="name of the node this plugin runs on"),
        Flag("namespace", "NAMESPACE", default="tpu-dra-driver",
             help="driver namespace (multiprocess daemon deployments land here)"),
        Flag("cdi-root", "CDI_ROOT", default="/var/run/cdi",
             help="directory for CDI spec files"),
        Flag("plugin-dir", "PLUGIN_DIR",
             default=f"/var/lib/kubelet/plugins/{TPU_DRIVER_NAME}",
             help="kubelet plugin dir (dra.sock, checkpoint, locks)"),
        Flag("registry-dir", "REGISTRY_DIR",
             default="/var/lib/kubelet/plugins_registry",
             help="kubelet plugin watcher registry dir"),
        Flag("driver-root", "TPU_DRIVER_ROOT", default="/",
             help="host root to resolve libtpu under"),
        Flag("kube-api-url", "KUBE_API_URL", default=None,
             help="API server URL (default: in-cluster config)"),
        Flag("healthcheck-port", "HEALTHCHECK_PORT", default=0, type=int,
             help="metrics/health HTTP port (0 = disabled)"),
        Flag("additional-codes-to-ignore", "ADDITIONAL_CODES_TO_IGNORE",
             default="", help="comma-separated health event codes to skip"),
        Flag("coordinator-image", "COORDINATOR_IMAGE",
             default="tpu-dra-driver:latest",
             help="image for per-claim multiprocess-coordinator "
                  "Deployments (set to the deployed driver image)"),
        Flag("tpuctl-path", "TPUCTL_PATH", default="",
             help="path to tpuctl (empty = direct libtpuinfo calls)"),
        feature_gate_flag(),
        *logging_flags(),
    ])


def main(argv=None) -> int:
    fs = flags()
    ns = fs.parse(argv)
    logger = setup_logging(ns.v, ns.log_json)
    apply_feature_gates(ns)
    fs.dump_config(ns, logger)
    debug.start_debug_signal_handlers()
    # SIGUSR1 -> flight-recorder dump (recent spans + fault firings +
    # queue events, SURVEY §19): the "what is this plugin doing RIGHT
    # NOW" lever for a wedged pod, next to the stack-dump handlers.
    trace.install_signal_handler()

    backend = get_backend()
    # Transient API-server failures (rolling upgrade, LB blips)
    # retry with jittered backoff instead of crash-looping the pod.
    client = RetryingApiClient(HttpApiClient(base_url=ns.kube_api_url))
    cdi = CDIHandler(ns.cdi_root, driver_root=ns.driver_root)
    checkpoints = CheckpointManager(ns.plugin_dir)

    ts_manager = None
    if featuregates.enabled(featuregates.TimeSlicingSettings):
        ts_manager = TimeSlicingManager(backend, tpuctl_path=ns.tpuctl_path or None)
    mp_manager = None
    if featuregates.enabled(featuregates.MultiprocessSupport):
        mp_manager = MultiprocessManager(
            backend, client, node_name=ns.node_name, namespace=ns.namespace,
            root_dir=f"{ns.plugin_dir}/multiprocess",
            image=ns.coordinator_image)

    pt_manager = None
    if featuregates.enabled(featuregates.PassthroughSupport):
        from tpu_dra.tpuplugin.passthrough import PassthroughManager, PciSysfs
        pt_manager = PassthroughManager(
            PciSysfs(root=os.environ.get("TPUINFO_SYSFS_ROOT", "") or "/"))
        # Fail fast like NewVfioPciManager: a node advertising passthrough
        # without vfio/IOMMU support would break every claim at prepare.
        pt_manager.prechecks()

    state = DeviceState(
        backend=backend, cdi=cdi, checkpoints=checkpoints,
        driver_name=TPU_DRIVER_NAME, node_name=ns.node_name,
        ts_manager=ts_manager, mp_manager=mp_manager,
        pt_manager=pt_manager)

    codes = [int(c) for c in ns.additional_codes_to_ignore.split(",") if c]
    driver = TpuDriver(
        state=state, client=client, driver_name=TPU_DRIVER_NAME,
        node_name=ns.node_name, plugin_dir=ns.plugin_dir,
        registry_dir=ns.registry_dir, additional_codes_to_ignore=codes)

    metrics_srv = None
    if ns.healthcheck_port:
        from tpu_dra.kubeletplugin.server import self_probe
        metrics_srv = MetricsServer(
            addr="0.0.0.0", port=ns.healthcheck_port,  # noqa: S104
            health_probe=lambda: self_probe(driver.server))
        metrics_srv.start()

    stop = threading.Event()

    def _on_stop_signal(signum, _frame):
        # SIGTERM is the hot-upgrade path (SURVEY §22): snapshot the
        # flight recorder on the way down — if the drain wedges or the
        # restart goes bad, the evidence of what was in flight at the
        # kill already exists on disk. Dump before set(): the main
        # thread starts the drain the moment stop fires.
        if signum == signal.SIGTERM:
            trace.dump_flight_recorder("sigterm")
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, _on_stop_signal)

    driver.start()
    logger.info("tpu kubelet plugin serving on %s (kubelet gRPC) + %s "
                "(framed fast path)", driver.server.dra_socket,
                driver.server.fast_socket)
    stop.wait()
    driver.shutdown()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
