"""Allocatable device model: TPU chips and TensorCore subslices.

Reference: cmd/gpu-kubelet-plugin/deviceinfo.go:40-253 + allocatable.go —
``AllocatableDevice`` is a tagged union (Gpu | Mig | Vfio) rendered into a
``resourceapi.Device`` with attributes and capacity. TPU translation:

- ``chip``     — a whole TPU chip (/dev/accelN). GPU analog.
- ``subslice`` — a contiguous TensorCore range of a chip; the MIG analog.
  Unlike MIG, a TPU subslice is purely logical (no char-dev per instance,
  SURVEY §2.9): prepare renders it as env restricting the container's
  libtpu to a core range and an HBM share. Like the reference's
  enumerateAllPossibleDevices (nvlib.go:134-183), every possible placement
  is advertised; the scheduler picks one.
- passthrough is a prepare-time mode on a chip (PassthroughConfig), not a
  distinct advertised device — mirroring how VFIO devices piggyback on the
  GPU device with a config marker.

Device names are DNS-label safe: ``chip-3``, ``chip-3-ss-1c-0`` (chip 3,
1-core subslice, placement 0).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra.native.tpuinfo import Chip

DEVICE_TYPE_CHIP = "chip"
DEVICE_TYPE_SUBSLICE = "subslice"


@dataclass(frozen=True)
class SubslicePlacement:
    """A specific core-range placement of a subslice profile on a chip."""
    chip: Chip
    core_count: int
    core_start: int

    @property
    def profile(self) -> str:
        return f"{self.core_count}c"

    @property
    def name(self) -> str:
        return f"chip-{self.chip.index}-ss-{self.profile}-{self.core_start}"

    @property
    def hbm_bytes(self) -> int:
        return self.chip.hbm_bytes * self.core_count // self.chip.tensorcore_count


def subslice_placements(chip: Chip) -> List[SubslicePlacement]:
    """All placements of all proper-subset profiles (1..cores-1 core sizes,
    aligned). A 2-core v5p chip yields 1c@0 and 1c@1; single-core chips
    yield none (nothing to subdivide)."""
    out: List[SubslicePlacement] = []
    size = 1
    while size < chip.tensorcore_count:
        for start in range(0, chip.tensorcore_count - size + 1, size):
            out.append(SubslicePlacement(chip, size, start))
        size *= 2
    return out


def chip_device_name(chip: Chip) -> str:
    return f"chip-{chip.index}"


@dataclass(frozen=True)
class AllocatableDevice:
    """Tagged union over chip / subslice (deviceinfo.go:40-88 analog)."""
    type: str
    chip: Chip
    subslice: Optional[SubslicePlacement] = None

    @property
    def name(self) -> str:
        if self.type == DEVICE_TYPE_SUBSLICE:
            return self.subslice.name
        return chip_device_name(self.chip)

    def to_resource_api(self) -> Dict:
        """Render the resourceapi.Device entry for the ResourceSlice
        (deviceinfo.go GetDevice :90-253 analog). Attribute names sit under
        the driver's implicit prefix; DeviceClass CEL selects on e.g.
        device.attributes['tpu.dev'].type == 'chip'."""
        chip = self.chip
        attrs: Dict[str, Dict] = {
            "type": {"string": self.type},
            "uuid": {"string": chip.uuid},
            "productName": {"string": f"tpu-{chip.generation}"},
            "generation": {"string": chip.generation},
            "driverVersion": {"version": _semverish(chip.driver_version)},
            "pciAddress": {"string": chip.pci_address},
            "sliceID": {"string": chip.slice_id},
            "workerIndex": {"int": chip.worker_index},
            "coordX": {"int": chip.coords[0]},
            "coordY": {"int": chip.coords[1]},
            "coordZ": {"int": chip.coords[2]},
            # Declared slice dims ("4x4x4"): lets CEL selectors constrain
            # by topology and the topology scorer bound wraparound.
            "sliceTopology": {"string": chip.slice_topology},
        }
        if self.type == DEVICE_TYPE_CHIP:
            capacity = {
                "hbm": {"value": str(chip.hbm_bytes)},
                "tensorcores": {"value": str(chip.tensorcore_count)},
            }
        else:
            ss = self.subslice
            attrs["parentUUID"] = {"string": chip.uuid}
            attrs["profile"] = {"string": ss.profile}
            attrs["coreStart"] = {"int": ss.core_start}
            capacity = {
                "hbm": {"value": str(ss.hbm_bytes)},
                "tensorcores": {"value": str(ss.core_count)},
            }
        return {"name": self.name, "attributes": attrs, "capacity": capacity}


def _semverish(version: str) -> str:
    """resourceapi version attributes must be semver; coerce or fall back."""
    parts = version.split("-")[0].split(".")
    if len(parts) == 3 and all(p.isdigit() for p in parts):
        return version.split("-")[0]
    return "0.0.0"


def enumerate_allocatable(chips: List[Chip],
                          include_subslices: bool = True) -> Dict[str, AllocatableDevice]:
    """All allocatable devices on this node, keyed by device name
    (enumerateAllPossibleDevices analog, nvlib.go:111-183)."""
    out: Dict[str, AllocatableDevice] = {}
    for chip in chips:
        dev = AllocatableDevice(type=DEVICE_TYPE_CHIP, chip=chip)
        out[dev.name] = dev
        if include_subslices:
            for ss in subslice_placements(chip):
                dev = AllocatableDevice(type=DEVICE_TYPE_SUBSLICE, chip=chip,
                                        subslice=ss)
                out[dev.name] = dev
    return out
