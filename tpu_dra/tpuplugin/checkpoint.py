"""Versioned, checksummed node-local checkpoint.

Reference: cmd/gpu-kubelet-plugin/checkpoint.go:10-122 + checkpointv.go:9-81
— a JSON checkpoint written through the kubelet checkpointmanager with
embedded checksums, versioned V1/V2 with bidirectional conversion so the
driver can be up- and downgraded without losing claim state
(exercised by tests/bats/test_cd_updowngrade.bats). Claim states
``PrepareStarted``/``PrepareCompleted`` make Prepare idempotent and crash
recovery safe (device_state.go:147-273).

V1 layout (older drivers): {"preparedClaims": {uid: {devices: [...]}}} — no
state field; presence implies completed.
V2 layout: {"preparedClaims": {uid: {state, claim: {name, namespace},
devices: [...]}}}.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu_dra.infra import vfs
from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry

log = logging.getLogger("tpu_dra.tpuplugin")

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"

# Cross-RPC journal observability (SURVEY §14): the perf tier's
# amortization tripwire reads the per-manager counters; these aggregate
# process-wide for dashboards.
JOURNAL_APPENDS = DefaultRegistry.counter(
    "tpu_dra_journal_appends_total",
    "append-only checkpoint journal records appended (one per "
    "prepare/unprepare group commit; the delta, not the full image)")
JOURNAL_GROUP_SYNCS = DefaultRegistry.counter(
    "tpu_dra_journal_group_syncs_total",
    "journal fdatasyncs actually issued; under concurrent RPCs one sync "
    "covers many appends (group commit), so this stays below "
    "tpu_dra_journal_appends_total under load")
JOURNAL_COMPACTIONS = DefaultRegistry.counter(
    "tpu_dra_journal_compactions_total",
    "journal compactions: full-image slot store + journal swap once the "
    "record lag crosses the bounded-lag threshold")
JOURNAL_LAG = DefaultRegistry.gauge(
    "tpu_dra_journal_lag_records",
    "journal records appended since the last compaction (recovery replay "
    "length; bounded by the compaction threshold)")
JOURNAL_WINDOW_HOLDS = DefaultRegistry.counter(
    "tpu_dra_journal_window_holds_total",
    "group-commit windows held by a sync leader: the adaptive barrier "
    "predicted co-committers from the recent arrival rate and waited a "
    "bounded window before the fdatasync; must stay 0 under idle or "
    "strictly sequential load")
JOURNAL_ROTATIONS = DefaultRegistry.counter(
    "tpu_dra_journal_rotations_total",
    "journal segment rotations: a fresh preallocated segment became the "
    "append target (at compaction, which also retires the old chain, or "
    "at the size roll that bounds any one segment)")


class CheckpointError(Exception):
    pass


# ---------------------------------------------------------------------------
# Binary journal encoding (SURVEY §23)
# ---------------------------------------------------------------------------
# The segmented journal frames every record with a fixed-width binary
# header and a self-describing binary payload — no per-record JSON on
# the hot path, and recovery validates raw bytes instead of re-
# serializing a parsed document to recompute its checksum:
#
#   segment file := MAGIC(8) record*  zeros-to-preallocation-end
#   record       := length(u32 LE) crc32(u32 LE) seq(u64 LE) type(u8)
#                   payload[length]
#
# The CRC covers seq + type + payload (packed exactly as written), so a
# record whose header or body took ANY damage fails closed; an all-zero
# header is the preallocated tail (the clean end of the segment). The
# payload is the group-commit delta dict encoded with the tag-length-
# value codec below — tags cover the full JSON value universe because
# per-claim ``devices`` records are opaque driver dicts.

SEG_MAGIC = b"TDRJWAL1"
_SEG_HDR_LEN = len(SEG_MAGIC)
_REC_HDR = struct.Struct("<IIQB")     # length, crc32, seq, type
_SEQ_TYPE = struct.Struct("<QB")      # the header fields the crc covers
_REC_DELTA = 1                        # group-commit delta record
_MAX_RECORD = 16 * 1024 * 1024        # sanity bound on a framed length
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")


def _enc_value(v, out: bytearray) -> None:
    """Tag-length-value encoder over the JSON value universe. Dict
    order is preserved as-is: the CRC covers the encoded bytes, so no
    canonical ordering is needed (unlike the JSON envelope, which had
    to re-serialize sorted on every read to re-derive the checksum)."""
    if v is None:
        out.append(0)
    elif v is True:
        out.append(2)
    elif v is False:
        out.append(1)
    elif isinstance(v, int):
        try:
            packed = _I64.pack(v)
        except struct.error:          # beyond i64: decimal-string tag
            b = str(v).encode()
            out.append(8)
            out += _U32.pack(len(b))
            out += b
        else:
            out.append(3)
            out += packed
    elif isinstance(v, float):
        out.append(4)
        out += _F64.pack(v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(5)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, (bytes, bytearray)):
        out.append(9)
        out += _U32.pack(len(v))
        out += v
    elif isinstance(v, (list, tuple)):
        out.append(6)
        out += _U32.pack(len(v))
        for item in v:
            _enc_value(item, out)
    elif isinstance(v, dict):
        out.append(7)
        out += _U32.pack(len(v))
        for k, item in v.items():
            kb = k.encode("utf-8")
            out += _U32.pack(len(kb))
            out += kb
            _enc_value(item, out)
    else:
        raise CheckpointError(
            f"unencodable journal value type {type(v).__name__}")


def _dec_value(buf: bytes, off: int):
    """-> (value, next_offset). Raises on any malformed input; the
    segment scanner treats that as a torn record (though the CRC gate
    in front of it makes a decode failure near-unreachable)."""
    tag = buf[off]
    off += 1
    if tag == 0:
        return None, off
    if tag == 1:
        return False, off
    if tag == 2:
        return True, off
    if tag == 3:
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == 4:
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag in (5, 8, 9):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        if off + n > len(buf):
            raise ValueError("truncated value")
        raw = buf[off:off + n]
        if tag == 5:
            return raw.decode("utf-8"), off + n
        if tag == 8:
            return int(raw.decode("ascii")), off + n
        return bytes(raw), off + n
    if tag == 6:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            item, off = _dec_value(buf, off)
            items.append(item)
        return items, off
    if tag == 7:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            kn = _U32.unpack_from(buf, off)[0]
            off += 4
            if off + kn > len(buf):
                raise ValueError("truncated key")
            k = buf[off:off + kn].decode("utf-8")
            off += kn
            d[k], off = _dec_value(buf, off)
        return d, off
    raise ValueError(f"bad value tag {tag}")


def _frame_record(seq: int, rtype: int, payload: bytes) -> bytes:
    crc = zlib.crc32(payload, zlib.crc32(_SEQ_TYPE.pack(seq, rtype)))
    return _REC_HDR.pack(len(payload), crc, seq, rtype) + payload


def _scan_segment(buf: bytes):
    """-> (records [(seq, delta_doc)...], valid_end, clean_tail).

    Walks the framed records from the magic to the first stop: the
    preallocated zero tail (clean), end-of-file on a record boundary
    (clean), or a record whose header/CRC/payload fails validation —
    the torn tail a crash legally shredded (not clean). ``valid_end``
    is where the next append belongs."""
    if len(buf) < _SEG_HDR_LEN or buf[:_SEG_HDR_LEN] != SEG_MAGIC:
        return [], 0, False
    records = []
    off = _SEG_HDR_LEN
    hdr = _REC_HDR
    while True:
        if off + hdr.size > len(buf):
            return records, off, buf.count(0, off) == len(buf) - off
        length, crc, seq, rtype = hdr.unpack_from(buf, off)
        if length == 0 and crc == 0 and seq == 0 and rtype == 0:
            # Preallocated zero tail — the clean end (a real record can
            # never frame this way: its CRC covers a nonzero seq).
            return records, off, buf.count(0, off) == len(buf) - off
        body = off + hdr.size
        if length > _MAX_RECORD or body + length > len(buf) or seq <= 0:
            return records, off, False
        payload = buf[body:body + length]
        if zlib.crc32(payload,
                      zlib.crc32(_SEQ_TYPE.pack(seq, rtype))) != crc:
            return records, off, False
        try:
            doc, dend = _dec_value(payload, 0)
        except (ValueError, IndexError, struct.error,
                UnicodeDecodeError):
            return records, off, False
        if dend != length or not isinstance(doc, dict):
            return records, off, False
        if rtype == _REC_DELTA:
            records.append((seq, doc))
        # Unknown record types: valid frame, skip the payload —
        # forward-compatibility for readers one version behind.
        off = body + length


@dataclass
class PreparedClaim:
    uid: str
    state: str = PREPARE_STARTED
    name: str = ""
    namespace: str = ""
    # Opaque per-driver device records (device names, cdi ids, config...)
    devices: List[Dict] = field(default_factory=list)

    def to_v2(self) -> Dict:
        return {"state": self.state,
                "claim": {"name": self.name, "namespace": self.namespace},
                "devices": self.devices}

    @classmethod
    def from_v2(cls, uid: str, doc: Dict) -> "PreparedClaim":
        claim = doc.get("claim") or {}
        return cls(uid=uid, state=doc.get("state", PREPARE_COMPLETED),
                   name=claim.get("name", ""), namespace=claim.get("namespace", ""),
                   devices=list(doc.get("devices") or []))


@dataclass
class Checkpoint:
    claims: Dict[str, PreparedClaim] = field(default_factory=dict)
    # Chip-quarantine ledger (SURVEY §18): chip uuid -> record dict
    # ({chip_index, reason, flaps, since, ttl_s}). Quarantine must
    # survive a driver restart — a flapping chip that crashed the plugin
    # would otherwise re-enter the inventory on recovery and flap the
    # scheduler all over again — so it rides the same durable state
    # machine as the claims: full map in every slot image, delta
    # snapshots in the journal (journal_commit(quarantine=True)).
    quarantine: Dict[str, Dict] = field(default_factory=dict)

    # -- versioned encodings ------------------------------------------------

    def to_v2_doc(self) -> Dict:
        doc = {
            "version": "v2",
            "preparedClaims": {uid: c.to_v2() for uid, c in self.claims.items()},
        }
        if self.quarantine:
            doc["quarantine"] = {uid: dict(rec)
                                 for uid, rec in self.quarantine.items()}
        return doc

    def to_v1_doc(self) -> Dict:
        """Downgrade view: V1 had no state machine — only completed claims
        are representable (checkpointv.go GetV1 analog)."""
        return {
            "version": "v1",
            "preparedClaims": {
                uid: {"devices": c.devices}
                for uid, c in self.claims.items() if c.state == PREPARE_COMPLETED
            },
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "Checkpoint":
        """Accept any known version and convert to latest
        (Checkpoint.ToLatestVersion analog)."""
        version = doc.get("version", "v1")
        prepared = doc.get("preparedClaims") or {}
        cp = cls()
        if version == "v1":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim(
                    uid=uid, state=PREPARE_COMPLETED,
                    devices=list(entry.get("devices") or []))
        elif version == "v2":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim.from_v2(uid, entry)
            cp.quarantine = {uid: dict(rec) for uid, rec in
                             (doc.get("quarantine") or {}).items()}
        else:
            raise CheckpointError(f"unknown checkpoint version {version!r}")
        return cp


class CheckpointManager:
    """Multi-slot in-place persistence with crc32 + sequence integrity.

    The kubelet checkpointmanager analog writes tmp-file + rename per save;
    on this path the rename and fresh-file block allocation made fdatasync
    behave like a full fsync (~0.23ms vs ~0.09ms for a same-size in-place
    overwrite, measured on the bench host) — and the checkpoint is stored
    TWICE per prepare (intent, then completed), squarely on the
    claim-to-ready hot path (SURVEY §3.2). So instead:

    - Every store writes the FULL state, in place, padded to a 4KiB
      multiple so repeat stores never change the file size (pure data
      overwrite -> cheap fdatasync).
    - The envelope carries a monotonic ``seq``; load() picks the highest
      valid-checksum slot.
    - Slots: the legacy-named primary ``checkpoint.json`` plus two side
      slots (``.b``/``.c``). Stores ping-pong between the side slots, so
      a torn write destroys at most the slot being written while the
      OTHER side slot still holds the previous full state — in-place
      overwrite never risks more than the in-flight store (matching the
      rename scheme's guarantee, plus recovery the rename scheme lacks).
    - Intent records (``PrepareStarted``, mid-prepare) write one side
      slot — a single cheap fdatasync on the claim-to-ready hot path.
      Terminal states (completed prepare, unprepare) write a side slot
      (data only, NOT synced) and then the primary with fdatasync — the
      primary is the terminal store's sole durability point, so the hot
      path pays exactly one device sync per store. The unsynced side
      write keeps recovery fresh: if a LATER primary overwrite tears,
      load() falls back to the most recent durable slot (this side copy
      if it reached the device, else the previous intent record) rather
      than an older settled state; and load_or_init() rewrites a damaged
      primary at the next start. A tear in the side slot itself loses
      nothing — its envelope fails the checksum and the synced primary
      holds the identical state.
    - A downgraded driver that only knows the single-file layout reads
      the primary = the latest settled state. If it then writes its own
      rename-style (seq-less) checkpoints, load() treats such a legacy
      primary as authoritative over any leftover side slots from before
      the downgrade (the old driver's last word is the truth);
      load_or_init() migrates it into the slot scheme immediately.
    """

    SLOT_PAD = 4096
    # Segment preallocation chunk: appends land inside already-allocated
    # blocks, so the group fdatasync stays a pure data sync (a growing
    # file would drag block-allocation metadata into every sync — the
    # same cost class the slot scheme's in-place overwrites avoid).
    # Segments are preallocated this much at creation and extended by
    # the same chunk when the tail outruns it.
    JOURNAL_ALLOC = 256 * 1024
    # Bounded-lag compaction threshold: recovery replays at most this
    # many journal records over the last compacted slot image, and the
    # journal file size stays bounded. One full-image slot store per
    # LAG appends amortizes to noise on the hot path.
    JOURNAL_COMPACT_LAG = 64
    # Size roll: a segment whose tail crosses this rotates to a fresh
    # segment WITHOUT a compaction — bounds any one file even while
    # compaction is degraded (ENOSPC on the slots), so recovery never
    # has to chew an unbounded segment.
    SEGMENT_ROLL = 1024 * 1024
    # Adaptive group-commit window (SURVEY §23): the sync leader holds
    # up to this long when the recent arrival rate predicts
    # co-committers, so coalescing is engineered instead of lucky.
    # Deadline-capped; never held under idle/sequential load (the
    # EWMA + concurrency-evidence test in journal_barrier).
    GROUP_WINDOW_US = 150.0
    # Hold only when the EWMA inter-append interval is within this many
    # windows. The factor is deliberately generous: on a GIL-serialized
    # single-core host a fully saturated pipeline still shows ~1ms
    # between appends, so a tight factor would never let the window fire
    # under exactly the load it exists for. Idle safety does NOT depend
    # on this number — the hold additionally requires concurrency
    # evidence (a newer append already landed, or a waiter is parked on
    # the barrier), so strictly sequential traffic never holds no matter
    # how small its inter-append interval looks.
    WINDOW_EWMA_FACTOR = 16.0
    _EWMA_ALPHA = 0.2

    def __init__(self, directory: str, filename: str = "checkpoint.json",
                 journal_compact_lag: Optional[int] = None,
                 group_window_us: Optional[float] = None,
                 segment_roll_bytes: Optional[int] = None):
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, filename)
        self._side_paths = (self._path + ".b", self._path + ".c")
        # Pre-segmented (JSON line-record) journal: read-only legacy
        # input to recovery; retired at the first compaction.
        self._legacy_path = self._path + ".journal"
        self._compact_lag = journal_compact_lag or self.JOURNAL_COMPACT_LAG
        if group_window_us is None:
            group_window_us = float(os.environ.get(
                "TPU_DRA_JOURNAL_WINDOW_US", str(self.GROUP_WINDOW_US)))
        self._window_s = max(group_window_us, 0.0) * 1e-6
        self._window_hold_max_s = self._window_s * self.WINDOW_EWMA_FACTOR
        self._segment_roll = segment_roll_bytes or self.SEGMENT_ROLL
        self._fds: Dict[str, int] = {}
        self._sizes: Dict[str, int] = {}
        # Observability counters (the group-commit regression tripwire,
        # hack/perf.sh): total store() calls, terminal (non-intent)
        # stores, and actual device syncs issued on slot data. A batch
        # of N claims must land exactly 1 terminal store = 1 slot sync;
        # N syncs here means the group commit silently degraded to
        # per-claim commits.
        self.stores: int = 0
        self.terminal_stores: int = 0
        self.slot_syncs: int = 0
        # Journal counters (the cross-RPC amortization tripwire): one
        # append per group commit; group syncs stay BELOW appends under
        # concurrent RPCs or the cross-RPC group commit degraded to a
        # sync per RPC.
        self.journal_appends: int = 0
        self.journal_group_syncs: int = 0
        self.journal_compactions: int = 0
        self.journal_lag: int = 0
        # Adaptive-window observability: holds must stay 0 under
        # sequential load (the perf tier's never-holds-idle tripwire);
        # rotations count fresh segments becoming the append target.
        self.journal_window_holds: int = 0
        self.journal_rotations: int = 0
        # Seed per-slot seqs from whatever is on disk so a manager that
        # stores before loading (e.g. a tool force-writing a downgrade
        # image) still supersedes stale slots from an earlier process,
        # and so side-slot ping-pong resumes on the older slot. Uses the
        # checksum-validating _load_slot: a corrupt slot seeds 0, sorting
        # it FIRST for overwrite — otherwise its stale-but-high seq would
        # steer the next store onto the last good side slot.
        self._slot_seqs: Dict[str, int] = {}
        for p in (self._path, *self._side_paths):
            r = self._load_slot(p)
            self._slot_seqs[p] = (r[0] or 0) if isinstance(r, tuple) else 0
        self._seq = max(self._slot_seqs.values())
        # Mutation side (append/compact) is additionally serialized by
        # the CALLER's data lock (DeviceState._lock — the manager is a
        # single-logical-writer component); _journal_lock only protects
        # the tail bookkeeping against the barrier side reading it.
        self._journal_lock = threading.Lock()
        # Group-commit barrier state: leader/follower fdatasync
        # coalescing (journal_barrier). Guards _synced_seq /
        # _appended_seq / _sync_in_flight. Condition over an EXPLICIT
        # Lock created in THIS frame (workqueue precedent): the lock
        # witness only instruments tpu_dra-created locks, and the
        # barrier never re-enters its own condition.
        self._sync_cond = threading.Condition(threading.Lock())
        self._sync_in_flight = False
        self._synced_seq = 0
        self._appended_seq = 0
        # True while a segment rotation's directory mutation (new
        # segment dirent, retired unlinks) still needs its directory
        # sync: the next group sync's leader retries it before any
        # post-rotation record may be declared durable.
        self._dir_dirty = False
        # Group-commit window state: EWMA of the inter-append interval
        # (written under _journal_lock; read racily by the leader — a
        # float read under the GIL) and whether a leader is currently
        # holding the window (so appends know to notify it).
        self._last_append_t: Optional[float] = None
        self._arrival_ewma: Optional[float] = None
        self._window_holding = False
        self._barrier_waiters = 0
        # Journal recovery scan: walk the legacy JSON journal plus the
        # binary segment chain to find the valid tail, seed _seq past
        # any journal record so new stores supersede the replay, and
        # count the replayable lag.
        records, active_end = self._scan_chain()
        if records:
            self._seq = max(self._seq, max(seq for seq, _ in records))
            best_slot = max(self._slot_seqs.values())
            self.journal_lag = sum(1 for seq, _ in records
                                   if seq > best_slot)
        seg_files = self._segment_files()
        if seg_files:
            self._segments = [idx for idx, _ in seg_files]
            self._active_seg = self._segments[-1]
            self._journal_fd = vfs.open_fd(seg_files[-1][1],
                                           os.O_RDWR | os.O_CREAT, 0o600)
            self._journal_alloc = os.fstat(self._journal_fd).st_size
            if active_end < _SEG_HDR_LEN:
                # The active segment never got (or tore) its magic —
                # rewrite it in place; appends follow it.
                self._pwrite_all(self._journal_fd, SEG_MAGIC, 0)
                active_end = _SEG_HDR_LEN
            self._journal_tail = active_end
        else:
            # First binary-format start (fresh dir, or a legacy-only
            # dir whose JSON journal stays read-only input): segment 0
            # becomes the append target, preallocated and with its
            # dirent made durable up front — the old scheme fsync'd the
            # fresh journal's dirent here too.
            self._segments = [0]
            self._active_seg = 0
            self._journal_fd = self._create_segment(0)
            self._journal_alloc = self.JOURNAL_ALLOC
            self._journal_tail = _SEG_HDR_LEN
            vfs.fsync_dir(os.path.dirname(self._path))
        self._synced_seq = self._appended_seq = self._seq
        JOURNAL_LAG.set(self.journal_lag)

    @property
    def path(self) -> str:
        return self._path

    def close(self) -> None:
        for fd in self._fds.values():
            try:
                vfs.close_fd(fd)
            except OSError:
                pass
        self._fds.clear()
        self._sizes.clear()
        if self._journal_fd is not None:
            try:
                vfs.close_fd(self._journal_fd)
            except OSError:
                pass
            self._journal_fd = None

    # -- segment plumbing ---------------------------------------------------

    def _seg_path(self, idx: int) -> str:
        return f"{self._path}.wal{idx:08d}"

    def _segment_files(self) -> List[Tuple[int, str]]:
        """Sorted (index, path) of the on-disk segment chain."""
        directory = os.path.dirname(self._path)
        prefix = os.path.basename(self._path) + ".wal"
        out = []
        try:
            names = os.listdir(directory)
        except FileNotFoundError:
            return []
        for name in names:
            if not name.startswith(prefix):
                continue
            try:
                idx = int(name[len(prefix):])
            except ValueError:
                continue
            out.append((idx, os.path.join(directory, name)))
        return sorted(out)

    @property
    def active_segment_path(self) -> str:
        """The segment currently absorbing appends (tests corrupt its
        tail to exercise the torn-tail drop)."""
        return self._seg_path(self._active_seg)

    def journal_segment_paths(self) -> List[str]:
        return [p for _, p in self._segment_files()]

    @staticmethod
    def _pwrite_all(fd: int, data: bytes, offset: int) -> None:
        off = 0
        while off < len(data):  # POSIX permits short writes
            n = vfs.pwrite(fd, data[off:], offset + off)
            if n <= 0:
                raise CheckpointError(f"short journal write at {offset}")
            off += n

    def _create_segment(self, idx: int) -> int:
        """Open a fresh preallocated segment: zeros out to the
        preallocation chunk (so the first group syncs stay pure data
        syncs), magic over the head. The caller owns the dirent sync."""
        fd = vfs.open_fd(self._seg_path(idx),
                         os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            vfs.preallocate(fd, 0, self.JOURNAL_ALLOC)
            self._pwrite_all(fd, SEG_MAGIC, 0)
        except BaseException:
            try:
                vfs.close_fd(fd)
            except OSError:
                pass
            try:
                vfs.unlink(self._seg_path(idx))
            except OSError:
                pass
            raise
        return fd

    def _envelope(self, payload: str, seq: int) -> bytes:
        """Checksummed envelope shared by slots and journal records.
        Assembled around the already-serialized payload (it is the
        checksum's exact input, so embedding it verbatim both avoids a
        second serialization and makes the checksum self-evidently
        consistent). `seqsum` covers the seq, which sits outside the
        data checksum (kept payload-only for legacy compatibility both
        ways): without it, a seq mangled into a different valid integer
        would silently reorder slot selection and could resurrect stale
        state. Legacy readers ignore the unknown keys."""
        return ('{"checksum": %d, "seq": %d, "seqsum": %d, "data": %s}'
                % (zlib.crc32(payload.encode()), seq,
                   zlib.crc32(b"%d" % seq), payload)).encode()

    def _write_slot(self, path: str, data: bytes, sync: bool = True) -> None:
        padded = data + b" " * (-len(data) % self.SLOT_PAD)
        fd = self._fds.get(path)
        if fd is None:
            existed = os.path.exists(path)
            fd = vfs.open_fd(path, os.O_RDWR | os.O_CREAT, 0o600)
            self._fds[path] = fd
            self._sizes[path] = os.fstat(fd).st_size
            if not existed:
                # Durable dirent for a NEW slot file: fdatasync persists
                # inode data, not the directory entry — without this a
                # post-crash reboot can show no file at all, losing the
                # store-before-side-effects guarantee. Once per file.
                vfs.fsync_dir(os.path.dirname(path))
        off = 0
        while off < len(padded):  # POSIX permits short writes
            n = vfs.pwrite(fd, padded[off:], off)
            if n <= 0:
                raise CheckpointError(f"short write to {path} at {off}")
            off += n
        if self._sizes[path] != len(padded):
            vfs.ftruncate(fd, len(padded))
            self._sizes[path] = len(padded)
        # Data-only sync: the durability point for the claim state machine
        # (store-before-side-effects). fdatasync is POSIX-but-not-macOS;
        # fall back to fsync there. sync=False callers (the terminal
        # store's side-slot copy) get durability from a later synced slot.
        if sync:
            vfs.fdatasync(fd)
            self.slot_syncs += 1

    def store(self, cp: Checkpoint, version: str = "v2",
              intent: bool = False) -> None:
        """Persist the full state. ``intent=True`` marks a transient
        mid-operation record (side slot only, one write); terminal stores
        write side-then-primary (see class doc for the crash analysis)."""
        # Injection site: store failure (ENOSPC, fsync EIO) — prepare and
        # unprepare must stay retryable/idempotent when the state machine
        # cannot persist.
        FAULTS.check("checkpoint.store", intent=intent)
        self.stores += 1
        if not intent:
            self.terminal_stores += 1
        doc = cp.to_v1_doc() if version == "v1" else cp.to_v2_doc()
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        self._seq += 1
        envelope = self._envelope(payload, self._seq)
        # Ping-pong: overwrite the STALER side slot, so the fresher one
        # still holds the previous state if this write tears.
        side = min(self._side_paths, key=lambda p: self._slot_seqs[p])
        # Intent stores sync the side slot (it is their durability point);
        # terminal stores leave it as a data-only recovery copy and sync
        # the primary below — one fdatasync either way (hot-path cost,
        # SURVEY §3.2).
        self._write_slot(side, envelope, sync=intent)
        self._slot_seqs[side] = self._seq
        if not intent:
            # In place, like the sides: the PrepareCompleted store IS on
            # the claim-to-ready path (a tmp+rename here measured +0.7ms
            # on p50). Residual risk accepted: a tear here leaves the
            # primary unparseable until the next driver start repairs it
            # (load_or_init) — only a crash followed by a downgrade to a
            # single-file-scheme binary WITHOUT an intervening new-driver
            # start ever surfaces it, and the side slots still hold the
            # full state for recovery.
            self._write_slot(self._path, envelope)
            self._slot_seqs[self._path] = self._seq
        # Injection site for torn writes: the armed action scribbles on
        # the just-written slot files; the next load must recover from
        # the surviving slots (crash-consistency chaos).
        FAULTS.check("checkpoint.corrupt",
                     paths=(side,) if intent else (side, self._path))

    def store_batch(self, cp: Checkpoint, *, present=(), absent=(),
                    version: str = "v2", intent: bool = False) -> None:
        """Multi-claim group commit: ONE slot write + ONE durable sync
        covering every claim the batch touched — N claims, 1 fdatasync,
        instead of the N the per-claim loop paid (SURVEY §9). The
        crash-consistency story is unchanged: the durable image is still
        the FULL state written through store(), so a crash before this
        call replays every member from its previous durable state and a
        crash after it finds every member settled together.

        `present`/`absent` are the commit's claim-level postconditions
        (uids the batch prepared / removed): a group commit whose
        in-memory state silently dropped a member — memory running ahead
        of or behind disk, the exact bug class chaos seed 5 found on the
        unprepare path — is refused here, before anything durable
        happens, instead of surfacing as a resurrected or lost claim at
        the next restart."""
        missing = [u for u in present if u not in cp.claims]
        lingering = [u for u in absent if u in cp.claims]
        if missing or lingering:
            raise CheckpointError(
                f"group commit inconsistent: missing={missing} "
                f"lingering={lingering}")
        self.store(cp, version=version, intent=intent)

    # ------------------------------------------------------------------
    # Binary segmented journal (SURVEY §14, rebuilt §23)
    # ------------------------------------------------------------------
    # The hot-path replacement for full-image terminal stores: each
    # prepare/unprepare group commit appends ONE binary delta record
    # (fixed-width checksummed framing, no per-record JSON), and
    # durability comes from journal_barrier's leader/follower group
    # fdatasync — concurrent RPCs whose barriers overlap share a single
    # device sync, and the leader's adaptive window turns lucky overlap
    # into engineered coalescing. The journal is a chain of
    # preallocated segment files (<checkpoint>.walNNNNNNNN): compaction
    # stores the full image through the slot scheme and RETIRES the old
    # chain behind a fresh segment (rotation + unlink — no
    # rewrite-and-rename), and an oversized segment rolls to a fresh
    # one even when compaction is degraded. Recovery = newest valid
    # slot image + replay of the legacy JSON journal (pre-segment
    # format, read-only) then the segment chain in order, stopping at
    # the first torn/invalid record (the tail a crash may legally
    # shred) — validated at the binary level, raw bytes against the
    # framed CRC.

    def journal_commit(self, cp: Checkpoint, *, present=(), absent=(),
                       intent: bool = False,
                       quarantine: bool = False) -> int:
        """Append one group-commit delta record; returns the sync token
        for journal_barrier. NOT durable until the barrier. Caller must
        hold its data lock (single logical writer — same contract as
        store()); the barrier must then be awaited WITHOUT that lock so
        concurrent RPCs coalesce their fdatasyncs.

        `present`/`absent` are both the postcondition check (as in
        store_batch) and the delta itself: present uids are serialized
        from `cp`, absent uids become removal markers.

        ``quarantine=True`` additionally snapshots the full quarantine
        ledger into the record (the map is O(chips-per-node), so a full
        snapshot per transition is cheaper than delta bookkeeping and
        makes replay order-insensitive: the highest-seq record wins)."""
        # Same site as the slot path: a journal append IS the hot-path
        # checkpoint store; chaos arms one site to break both schemes.
        FAULTS.check("checkpoint.store", intent=intent)
        # Injection site: the append itself fails (ENOSPC on the
        # journal) while the slot scheme may still work — the caller
        # must unwind exactly like a failed terminal store.
        FAULTS.check("prepare.journal_append", intent=intent)
        missing = [u for u in present if u not in cp.claims]
        lingering = [u for u in absent if u in cp.claims]
        if missing or lingering:
            raise CheckpointError(
                f"group commit inconsistent: missing={missing} "
                f"lingering={lingering}")
        delta = {"intent": bool(intent),
                 "upsert": {uid: cp.claims[uid].to_v2() for uid in present},
                 "remove": sorted(absent)}
        if quarantine:
            delta["quarantine"] = {uid: dict(rec)
                                   for uid, rec in cp.quarantine.items()}
        payload = bytearray()
        _enc_value(delta, payload)
        payload = bytes(payload)
        now = time.monotonic()
        with self._journal_lock:
            fd = self._ensure_journal_fd()
            self._seq += 1
            seq = self._seq
            record = _frame_record(seq, _REC_DELTA, payload)
            end = self._journal_tail + len(record)
            if end > self._journal_alloc:
                # Extend the preallocation ahead of the tail so the
                # group sync never pays block-allocation metadata.
                grow = max(self.JOURNAL_ALLOC, len(record))
                vfs.preallocate(fd, self._journal_alloc, grow)
                self._journal_alloc += grow
            self._pwrite_all(fd, record, self._journal_tail)
            self._journal_tail = end
            self.journal_appends += 1
            self.journal_lag += 1
            JOURNAL_APPENDS.inc()
            JOURNAL_LAG.set(self.journal_lag)
            # Arrival-rate EWMA feeding the adaptive group-commit
            # window: a short recent inter-append interval predicts a
            # co-committer will land inside a held window.
            prev = self._last_append_t
            self._last_append_t = now
            if prev is not None:
                dt = now - prev
                self._arrival_ewma = dt if self._arrival_ewma is None \
                    else (self._EWMA_ALPHA * dt
                          + (1.0 - self._EWMA_ALPHA) * self._arrival_ewma)
        with self._sync_cond:
            self._appended_seq = seq
            if self._window_holding:
                # A leader is holding the group-commit window for
                # exactly this append — wake it so the covering sync
                # can include the record without burning the deadline.
                self._sync_cond.notify_all()
        # (No checkpoint.corrupt injection here: tearing the appended
        # record would shred the commit's ONLY copy while the RPC still
        # reports success — a torn journal tail is only reachable
        # through a crash, which is exactly what drmc's torn crash
        # variant models. The slot scheme keeps its injection because
        # it writes two copies and recovery uses the survivor.)
        if self.journal_lag >= self._compact_lag:
            self._compact(cp)
        elif self._journal_tail >= self._segment_roll:
            self._roll_segment()
        return seq

    def journal_barrier(self, token: int, *, urgent: bool = False) -> None:
        """Block until every journal record up to `token` is durable.
        Leader/follower group commit: the first waiter to find no sync
        in flight becomes the leader and issues ONE fdatasync covering
        the whole appended tail; followers whose records that sync
        covers just wait — N concurrent RPCs, 1 device sync. Call
        WITHOUT holding the data lock, or nothing can coalesce.

        The leader additionally runs the ADAPTIVE GROUP-COMMIT WINDOW
        (SURVEY §23): when the recent arrival rate predicts a
        co-committer inside ~one window AND there is live concurrency
        evidence (records already appended past this token, or waiters
        queued behind an earlier sync), it holds a bounded,
        deadline-capped window before issuing the sync so the incoming
        append shares it. Under idle or strictly sequential load the
        evidence test fails (a lone caller's own token is always the
        newest append and nobody waits) and the sync is immediate —
        the window NEVER taxes the uncontended path. ``urgent=True``
        (shutdown drain, error-path unwinds) skips the window
        outright."""
        while True:
            with self._sync_cond:
                if self._synced_seq >= token:
                    return
                if self._sync_in_flight:
                    self._barrier_waiters += 1
                    try:
                        self._sync_cond.wait()
                    finally:
                        self._barrier_waiters -= 1
                    continue
                self._sync_in_flight = True
                if not urgent and self._window_s > 0.0:
                    ewma = self._arrival_ewma
                    if (ewma is not None
                            and ewma <= self._window_hold_max_s
                            and (self._appended_seq > token
                                 or self._barrier_waiters > 0)):
                        self.journal_window_holds += 1
                        JOURNAL_WINDOW_HOLDS.inc()
                        self._window_holding = True
                        deadline = time.monotonic() + self._window_s
                        while True:
                            rem = deadline - time.monotonic()
                            if rem <= 0:
                                break
                            # Woken by each append landing inside the
                            # window; the deadline caps the hold no
                            # matter how fast they come.
                            self._sync_cond.wait(rem)
                        self._window_holding = False
                target = self._appended_seq
                dir_dirty = self._dir_dirty
                with self._journal_lock:
                    fd = self._ensure_journal_fd()
            try:
                vfs.fdatasync(fd)
                if dir_dirty:
                    # A segment rotation's directory mutation is still
                    # awaiting its sync: without it a crash could
                    # recover a dirent-less active segment and lose
                    # every post-rotation record this fdatasync just
                    # settled into the new inode.
                    vfs.fsync_dir(os.path.dirname(self._path))
            except BaseException:
                with self._sync_cond:
                    self._sync_in_flight = False
                    self._sync_cond.notify_all()
                raise
            with self._sync_cond:
                self._sync_in_flight = False
                if dir_dirty:
                    self._dir_dirty = False
                self._synced_seq = max(self._synced_seq, target)
                self.journal_group_syncs += 1
                JOURNAL_GROUP_SYNCS.inc()
                self._sync_cond.notify_all()

    def journal_flush(self) -> None:
        """Barrier over everything appended so far — the clean-shutdown
        journal barrier (SURVEY §22): after the drain window finishes
        the last in-flight batch, this settles its records so the next
        incarnation's recovery scan replays a complete tail instead of
        racing an unsynced one. Urgent: a drain must not sit out a
        group-commit window waiting for co-committers that the
        shutdown already stopped admitting."""
        with self._sync_cond:
            token = self._appended_seq
        self.journal_barrier(token, urgent=True)

    def _ensure_journal_fd(self) -> int:
        """Reopen the active segment's fd after close() — managers
        outlive the DeviceState that closed them in test/recovery
        rebuilds, exactly like the lazily-reopened slot fds. Caller
        holds _journal_lock. The tail survives (same file, same
        process); only the allocation is re-read."""
        if self._journal_fd is None:
            self._journal_fd = vfs.open_fd(
                self._seg_path(self._active_seg),
                os.O_RDWR | os.O_CREAT, 0o600)
            self._journal_alloc = os.fstat(self._journal_fd).st_size
            if self._journal_alloc < _SEG_HDR_LEN:
                # Externally truncated/fresh file: restore the magic so
                # recovery recognizes the segment.
                self._pwrite_all(self._journal_fd, SEG_MAGIC, 0)
                self._journal_alloc = _SEG_HDR_LEN
                self._journal_tail = max(self._journal_tail,
                                         _SEG_HDR_LEN)
        return self._journal_fd

    def _compact(self, cp: Checkpoint) -> None:
        """Bounded-lag compaction: persist the full image through the
        slot scheme (durable, seq past every journal record), then
        rotate to a fresh segment and retire the old chain — unlink,
        not rewrite-and-rename. Crash-safe at every step: after the
        slot store every journal record is stale (seq <= slot seq,
        recovery skips them), a rotation that never lands just leaves
        stale records behind, and a retired segment whose unlink never
        persisted resurrects only stale records. Failure is DEGRADED,
        not raised — compaction is maintenance; the commit it rode in
        on already appended, so surfacing an error here would un-report
        a success. The lag keeps growing and the next append retries."""
        try:
            # Injection site: compaction fails (slot ENOSPC, segment
            # create EIO) — the journal must keep absorbing appends and
            # lag must recover once the fault clears.
            FAULTS.check("prepare.journal_compact")
            self.store(cp)
            self._retire_segments(self._seq)
            self.journal_compactions += 1
            JOURNAL_COMPACTIONS.inc()
        except Exception:  # noqa: BLE001 — maintenance must not fail
            # the commit; bounded lag degrades to growing lag until the
            # fault clears (metric + retry on the next append).
            log.warning("journal compaction failed (lag %d, retrying on "
                        "next append)", self.journal_lag, exc_info=True)

    def _retire_segments(self, settled_seq: int) -> None:
        """Rotate to a fresh preallocated segment and retire the whole
        old chain (plus the legacy JSON journal) after a full slot
        store settled everything up to `settled_seq`. Waits out an
        in-flight group sync so the old fd is never closed under it.

        The fresh segment is fully created (preallocation + magic)
        BEFORE the switch, so there is no failure window in which the
        manager could keep appending to a retired file — and the
        directory mutations (new dirent, unlinks) may defer their sync:
        the dirty flag hands it to the next group sync's leader, which
        must complete it before any post-rotation record is declared
        durable."""
        with self._sync_cond:
            while self._sync_in_flight:
                self._sync_cond.wait()
            new_idx = self._active_seg + 1
            new_fd = self._create_segment(new_idx)
            old_fd = self._journal_fd
            retired = [i for i in self._segments if i != new_idx]
            self._segments = [new_idx]
            self._active_seg = new_idx
            self._journal_fd = new_fd
            with self._journal_lock:
                self._journal_tail = _SEG_HDR_LEN
                self._journal_alloc = self.JOURNAL_ALLOC
                self.journal_lag = 0
            self._synced_seq = max(self._synced_seq, settled_seq)
            self._dir_dirty = True
            self.journal_rotations += 1
            JOURNAL_ROTATIONS.inc()
            JOURNAL_LAG.set(0)
            self._sync_cond.notify_all()
        if old_fd is not None:
            try:
                vfs.close_fd(old_fd)
            except OSError:
                pass
        # Retire the stale chain: every record in it is <= settled_seq,
        # so a failed (or crash-lost) unlink only resurrects records
        # recovery skips anyway.
        for idx in retired:
            try:
                vfs.unlink(self._seg_path(idx))
            except OSError:
                log.warning("retired segment unlink failed: %s",
                            self._seg_path(idx), exc_info=True)
        try:
            vfs.unlink(self._legacy_path)
        except FileNotFoundError:
            pass
        except OSError:
            log.warning("legacy journal unlink failed", exc_info=True)
        try:
            vfs.fsync_dir(os.path.dirname(self._path))
            with self._sync_cond:
                self._dir_dirty = False
        except OSError:
            log.warning("segment rotation dir sync failed; retrying at "
                        "the next group sync", exc_info=True)

    def _roll_segment(self) -> None:
        """Size roll: the active segment outgrew the bound, so settle
        its tail and continue in a fresh segment WITHOUT a compaction —
        the old segment's records are still live (no slot image
        supersedes them), so it stays in the chain until the next
        compaction retires it. Degraded on failure: appends simply
        continue in the oversized segment and the next append retries."""
        try:
            with self._sync_cond:
                while self._sync_in_flight:
                    self._sync_cond.wait()
                with self._journal_lock:
                    if self._journal_tail < self._segment_roll:
                        return      # a concurrent roll already landed
                old_fd = self._ensure_rolled_preconditions_locked()
                # Settle the old tail before abandoning its fd: barrier
                # tokens for those records must never be vouched for by
                # a sync on the NEW segment's fd.
                vfs.fdatasync(old_fd)
                self.journal_group_syncs += 1
                JOURNAL_GROUP_SYNCS.inc()
                self._synced_seq = max(self._synced_seq,
                                       self._appended_seq)
                new_idx = self._active_seg + 1
                new_fd = self._create_segment(new_idx)
                self._segments.append(new_idx)
                self._active_seg = new_idx
                self._journal_fd = new_fd
                with self._journal_lock:
                    self._journal_tail = _SEG_HDR_LEN
                    self._journal_alloc = self.JOURNAL_ALLOC
                self._dir_dirty = True
                self.journal_rotations += 1
                JOURNAL_ROTATIONS.inc()
                self._sync_cond.notify_all()
            try:
                vfs.close_fd(old_fd)
            except OSError:
                pass
            try:
                vfs.fsync_dir(os.path.dirname(self._path))
                with self._sync_cond:
                    self._dir_dirty = False
            except OSError:
                log.warning("segment roll dir sync failed; retrying at "
                            "the next group sync", exc_info=True)
        except Exception:  # noqa: BLE001 — maintenance must not fail
            # the commit that triggered the roll.
            log.warning("segment roll failed (tail %d); retrying on "
                        "next append", self._journal_tail, exc_info=True)

    def _ensure_rolled_preconditions_locked(self) -> int:
        """Roll prerequisites (caller holds _sync_cond, no sync in
        flight): a pending directory sync must land FIRST — the roll is
        about to bump _synced_seq past records whose segment dirent may
        not be durable yet — and the fd must be open."""
        if self._dir_dirty:
            vfs.fsync_dir(os.path.dirname(self._path))
            self._dir_dirty = False
        with self._journal_lock:
            return self._ensure_journal_fd()

    def _read_legacy_journal(self):
        """-> [(seq, delta_doc)...] from the pre-segment JSON
        line-record journal (read-only legacy input; the first
        compaction retires the file). Stops at the first invalid line:
        a torn tail, preallocated zeros, or garbage."""
        try:
            with open(self._legacy_path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return []
        records = []
        off = 0
        while True:
            nl = buf.find(b"\n", off)
            if nl < 0:
                break
            line = buf[off:nl]
            if not line.startswith(b"{"):
                break
            try:
                envelope = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                break
            doc = (envelope.get("data")
                   if isinstance(envelope, dict) else None)
            seq = envelope.get("seq") if isinstance(envelope, dict) else None
            if doc is None or not isinstance(seq, int):
                break
            payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
            if zlib.crc32(payload.encode()) != envelope.get("checksum"):
                break
            if envelope.get("seqsum") != zlib.crc32(b"%d" % seq):
                break
            records.append((seq, doc))
            off = nl + 1
        return records

    def _scan_chain(self):
        """-> ([(seq, delta_doc)...], active_valid_end). The full
        replayable record stream: legacy JSON journal first (it always
        predates any binary segment — the first compaction retires it),
        then the segment chain in index order. The first torn/invalid
        record drops everything after it — only the chain's true tail
        can legally tear (crashes append at the end), so the drop is
        exactly the torn suffix. ``active_valid_end`` is the append
        offset inside the LAST segment (0 when none exist)."""
        records = self._read_legacy_journal()
        active_end = 0
        broken = False
        for idx, path in self._segment_files():
            active_end = 0
            if broken:
                continue     # chain already torn: later records dead
            try:
                with open(path, "rb") as f:
                    buf = f.read()
            except (FileNotFoundError, OSError):
                broken = True
                continue
            segment_records, valid_end, clean = _scan_segment(buf)
            records.extend(segment_records)
            active_end = valid_end
            if not clean:
                broken = True
        return records, active_end

    def _replay_journal(self, cp: Optional[Checkpoint],
                        base_seq: int) -> Optional[Checkpoint]:
        """Apply journal records with seq > base_seq (the slot image's)
        over `cp`, in append order. Records at or below the base are the
        compaction's leftovers; the torn tail was already dropped by the
        scan."""
        records, _ = self._scan_chain()
        for seq, doc in records:
            if seq <= base_seq:
                continue
            if cp is None:
                cp = Checkpoint()
            for uid, entry in (doc.get("upsert") or {}).items():
                cp.claims[uid] = PreparedClaim.from_v2(uid, entry)
            for uid in doc.get("remove") or ():
                cp.claims.pop(uid, None)
            if "quarantine" in doc:
                # Full-snapshot semantics: the record's ledger replaces
                # the image's (append order = seq order, so the last
                # replayed snapshot is the newest).
                cp.quarantine = {uid: dict(rec) for uid, rec in
                                 (doc.get("quarantine") or {}).items()}
            self._seq = max(self._seq, seq)
        return cp

    def _load_slot(self, path: str):
        """-> (seq | None-for-legacy, doc) or None (absent/empty) or
        'corrupt'. The doc is NOT deserialized into a Checkpoint here so
        version-compat policy stays in load()."""
        try:
            with open(path) as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        if not raw.strip():
            return None
        try:
            envelope = json.loads(raw)
        except json.JSONDecodeError:
            return "corrupt"
        doc = envelope.get("data") if isinstance(envelope, dict) else None
        if doc is None:
            return "corrupt"
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        if zlib.crc32(payload.encode()) != envelope.get("checksum"):
            return "corrupt"
        seq = envelope.get("seq")
        if seq is not None:
            # seq sits outside the checksum (which covers only `data`, for
            # legacy compatibility both ways): a mangled seq must degrade
            # to "corrupt slot", not crash slot selection.
            try:
                seq = int(seq)
            except (ValueError, TypeError):
                return "corrupt"
            # seqsum (when present — absent in pre-seqsum envelopes, whose
            # seq stays best-effort) catches a seq mangled into a DIFFERENT
            # valid integer, which would silently reorder slot selection.
            seqsum = envelope.get("seqsum")
            if seqsum is not None and seqsum != zlib.crc32(b"%d" % seq):
                return "corrupt"
        return seq, doc

    def load(self) -> Optional[Checkpoint]:
        """None when no checkpoint exists (first start). A *legacy*
        (seq-less, rename-scheme) primary is authoritative: it means a
        downgraded driver wrote last, whatever side slots AND journal
        records remain predate the downgrade, and nothing is replayed
        over it. Otherwise the highest-seq valid slot wins and the
        journal tail (records with seq beyond the slot image) is
        replayed over it. Raises only when every present slot is
        corrupt."""
        # (The __init__ seq seeding also parsed these slots; re-reading
        # here costs ~3 4KiB files once per process and keeps load()
        # correct after intervening stores — not worth a cache.)
        results = {p: self._load_slot(p)
                   for p in (self._path, *self._side_paths)}
        primary = results[self._path]
        if isinstance(primary, tuple) and primary[0] is None:
            return Checkpoint.from_doc(primary[1])
        valid = [r for r in results.values()
                 if isinstance(r, tuple) and r[0] is not None]
        if valid:
            seq, doc = max(valid, key=lambda r: r[0])
            self._seq = max(self._seq, seq)
            return self._replay_journal(Checkpoint.from_doc(doc), seq)
        corrupt = [p for p, r in results.items() if r == "corrupt"]
        if corrupt:
            # Every slot shredded: fail LOUDLY. The journal is NOT a
            # substitute image here — after any compaction it holds
            # only post-compaction deltas, and nothing in the file
            # attests full coverage; replaying it from empty would
            # silently drop every earlier claim (leaked side effects,
            # double allocation) behind a clean-looking startup.
            raise CheckpointError(
                f"checkpoint corrupt, no valid slot: {', '.join(corrupt)}")
        # No slot file at all (a state no crash can produce — slot
        # dirents are fsync'd at creation and every journal record
        # postdates the first store): if a journal is nonetheless
        # present, replaying what it holds beats silently starting
        # fresh over possibly-live side effects.
        return self._replay_journal(None, 0)

    def load_or_init(self) -> Checkpoint:
        """Load at process start, initializing an empty checkpoint on
        first run — and ALWAYS re-storing what was loaded. The store
        repairs whatever the load tolerated (a slot torn by a crash, a
        stale loser slot, a journal tail) so the every-slot-valid
        invariant is restored before new in-place overwrites put it at
        risk again, it migrates a legacy (seq-less, rename-scheme)
        primary into the slot scheme so a post-upgrade crash cannot
        out-rank newer intent records with the legacy file, and it
        folds the replayed journal tail into the compacted image (the
        journal restarts empty: startup is a free compaction point)."""
        cp = self.load()
        if cp is None:
            cp = Checkpoint()
        # A PrepareStarted claim recovered here came from a crash mid-
        # prepare: persisting it terminally is the intended graduation to
        # a rollback record (same class as the failed-prepare store,
        # tpuplugin/device_state.py error path) — v2 readers on both
        # sides of an up/downgrade handle the state, and the v1 view
        # drops non-completed claims by construction (to_v1_doc).
        self.store(cp)
        if (self._journal_tail > _SEG_HDR_LEN or len(self._segments) > 1
                or os.path.exists(self._legacy_path)):
            # Startup is a free compaction point: retire the replayed
            # chain (and fold a legacy JSON journal into the binary
            # scheme — the repair store above IS its migrated image).
            try:
                self._retire_segments(self._seq)
            except Exception:  # noqa: BLE001 — the repair store above
                # already made every journal record stale; a failed
                # rotation only leaves dead records to skip on the next
                # load.
                log.warning("journal rotation at startup failed",
                            exc_info=True)
        return cp
