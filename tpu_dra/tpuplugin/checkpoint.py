"""Versioned, checksummed node-local checkpoint.

Reference: cmd/gpu-kubelet-plugin/checkpoint.go:10-122 + checkpointv.go:9-81
— a JSON checkpoint written through the kubelet checkpointmanager with
embedded checksums, versioned V1/V2 with bidirectional conversion so the
driver can be up- and downgraded without losing claim state
(exercised by tests/bats/test_cd_updowngrade.bats). Claim states
``PrepareStarted``/``PrepareCompleted`` make Prepare idempotent and crash
recovery safe (device_state.go:147-273).

V1 layout (older drivers): {"preparedClaims": {uid: {devices: [...]}}} — no
state field; presence implies completed.
V2 layout: {"preparedClaims": {uid: {state, claim: {name, namespace},
devices: [...]}}}.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"


class CheckpointError(Exception):
    pass


@dataclass
class PreparedClaim:
    uid: str
    state: str = PREPARE_STARTED
    name: str = ""
    namespace: str = ""
    # Opaque per-driver device records (device names, cdi ids, config...)
    devices: List[Dict] = field(default_factory=list)

    def to_v2(self) -> Dict:
        return {"state": self.state,
                "claim": {"name": self.name, "namespace": self.namespace},
                "devices": self.devices}

    @classmethod
    def from_v2(cls, uid: str, doc: Dict) -> "PreparedClaim":
        claim = doc.get("claim") or {}
        return cls(uid=uid, state=doc.get("state", PREPARE_COMPLETED),
                   name=claim.get("name", ""), namespace=claim.get("namespace", ""),
                   devices=list(doc.get("devices") or []))


@dataclass
class Checkpoint:
    claims: Dict[str, PreparedClaim] = field(default_factory=dict)

    # -- versioned encodings ------------------------------------------------

    def to_v2_doc(self) -> Dict:
        return {
            "version": "v2",
            "preparedClaims": {uid: c.to_v2() for uid, c in self.claims.items()},
        }

    def to_v1_doc(self) -> Dict:
        """Downgrade view: V1 had no state machine — only completed claims
        are representable (checkpointv.go GetV1 analog)."""
        return {
            "version": "v1",
            "preparedClaims": {
                uid: {"devices": c.devices}
                for uid, c in self.claims.items() if c.state == PREPARE_COMPLETED
            },
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "Checkpoint":
        """Accept any known version and convert to latest
        (Checkpoint.ToLatestVersion analog)."""
        version = doc.get("version", "v1")
        prepared = doc.get("preparedClaims") or {}
        cp = cls()
        if version == "v1":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim(
                    uid=uid, state=PREPARE_COMPLETED,
                    devices=list(entry.get("devices") or []))
        elif version == "v2":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim.from_v2(uid, entry)
        else:
            raise CheckpointError(f"unknown checkpoint version {version!r}")
        return cp


class CheckpointManager:
    """Atomic file persistence with crc32 integrity (the kubelet
    checkpointmanager-with-checksum analog)."""

    def __init__(self, directory: str, filename: str = "checkpoint.json"):
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, filename)

    @property
    def path(self) -> str:
        return self._path

    def store(self, cp: Checkpoint, version: str = "v2") -> None:
        doc = cp.to_v1_doc() if version == "v1" else cp.to_v2_doc()
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        # Envelope assembled around the already-serialized payload (it is
        # the checksum's exact input, so embedding it verbatim both avoids
        # a second serialization and makes the checksum self-evidently
        # consistent). "checksum" < "data": key order matches the sorted
        # output load() re-derives.
        envelope = ('{"checksum": %d, "data": %s}'
                    % (zlib.crc32(payload.encode()), payload))
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(envelope)
            f.flush()
            # Data-only sync: the durability point for the claim state
            # machine (prepare's store-before-side-effects contract).
            # File metadata is irrelevant here and the plain fsync was the
            # single largest cost in the claim-to-ready hot path
            # (bench prepare_breakdown: ~0.28ms of a ~0.42ms store).
            # fdatasync is POSIX-but-not-macOS; fall back to fsync there.
            getattr(os, "fdatasync", os.fsync)(f.fileno())
        os.replace(tmp, self._path)

    def load(self) -> Optional[Checkpoint]:
        """None when no checkpoint exists (first start)."""
        try:
            with open(self._path) as f:
                envelope = json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError as e:
            raise CheckpointError(f"corrupt checkpoint {self._path}: {e}") from e
        doc = envelope.get("data")
        if doc is None:
            raise CheckpointError(f"checkpoint {self._path} missing data")
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        if zlib.crc32(payload.encode()) != envelope.get("checksum"):
            raise CheckpointError(f"checkpoint {self._path} checksum mismatch")
        return Checkpoint.from_doc(doc)

    def load_or_init(self) -> Checkpoint:
        cp = self.load()
        if cp is None:
            cp = Checkpoint()
            self.store(cp)
        return cp
