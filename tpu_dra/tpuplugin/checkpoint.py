"""Versioned, checksummed node-local checkpoint.

Reference: cmd/gpu-kubelet-plugin/checkpoint.go:10-122 + checkpointv.go:9-81
— a JSON checkpoint written through the kubelet checkpointmanager with
embedded checksums, versioned V1/V2 with bidirectional conversion so the
driver can be up- and downgraded without losing claim state
(exercised by tests/bats/test_cd_updowngrade.bats). Claim states
``PrepareStarted``/``PrepareCompleted`` make Prepare idempotent and crash
recovery safe (device_state.go:147-273).

V1 layout (older drivers): {"preparedClaims": {uid: {devices: [...]}}} — no
state field; presence implies completed.
V2 layout: {"preparedClaims": {uid: {state, claim: {name, namespace},
devices: [...]}}}.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.infra import vfs
from tpu_dra.infra.faults import FAULTS

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"


class CheckpointError(Exception):
    pass


@dataclass
class PreparedClaim:
    uid: str
    state: str = PREPARE_STARTED
    name: str = ""
    namespace: str = ""
    # Opaque per-driver device records (device names, cdi ids, config...)
    devices: List[Dict] = field(default_factory=list)

    def to_v2(self) -> Dict:
        return {"state": self.state,
                "claim": {"name": self.name, "namespace": self.namespace},
                "devices": self.devices}

    @classmethod
    def from_v2(cls, uid: str, doc: Dict) -> "PreparedClaim":
        claim = doc.get("claim") or {}
        return cls(uid=uid, state=doc.get("state", PREPARE_COMPLETED),
                   name=claim.get("name", ""), namespace=claim.get("namespace", ""),
                   devices=list(doc.get("devices") or []))


@dataclass
class Checkpoint:
    claims: Dict[str, PreparedClaim] = field(default_factory=dict)

    # -- versioned encodings ------------------------------------------------

    def to_v2_doc(self) -> Dict:
        return {
            "version": "v2",
            "preparedClaims": {uid: c.to_v2() for uid, c in self.claims.items()},
        }

    def to_v1_doc(self) -> Dict:
        """Downgrade view: V1 had no state machine — only completed claims
        are representable (checkpointv.go GetV1 analog)."""
        return {
            "version": "v1",
            "preparedClaims": {
                uid: {"devices": c.devices}
                for uid, c in self.claims.items() if c.state == PREPARE_COMPLETED
            },
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "Checkpoint":
        """Accept any known version and convert to latest
        (Checkpoint.ToLatestVersion analog)."""
        version = doc.get("version", "v1")
        prepared = doc.get("preparedClaims") or {}
        cp = cls()
        if version == "v1":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim(
                    uid=uid, state=PREPARE_COMPLETED,
                    devices=list(entry.get("devices") or []))
        elif version == "v2":
            for uid, entry in prepared.items():
                cp.claims[uid] = PreparedClaim.from_v2(uid, entry)
        else:
            raise CheckpointError(f"unknown checkpoint version {version!r}")
        return cp


class CheckpointManager:
    """Multi-slot in-place persistence with crc32 + sequence integrity.

    The kubelet checkpointmanager analog writes tmp-file + rename per save;
    on this path the rename and fresh-file block allocation made fdatasync
    behave like a full fsync (~0.23ms vs ~0.09ms for a same-size in-place
    overwrite, measured on the bench host) — and the checkpoint is stored
    TWICE per prepare (intent, then completed), squarely on the
    claim-to-ready hot path (SURVEY §3.2). So instead:

    - Every store writes the FULL state, in place, padded to a 4KiB
      multiple so repeat stores never change the file size (pure data
      overwrite -> cheap fdatasync).
    - The envelope carries a monotonic ``seq``; load() picks the highest
      valid-checksum slot.
    - Slots: the legacy-named primary ``checkpoint.json`` plus two side
      slots (``.b``/``.c``). Stores ping-pong between the side slots, so
      a torn write destroys at most the slot being written while the
      OTHER side slot still holds the previous full state — in-place
      overwrite never risks more than the in-flight store (matching the
      rename scheme's guarantee, plus recovery the rename scheme lacks).
    - Intent records (``PrepareStarted``, mid-prepare) write one side
      slot — a single cheap fdatasync on the claim-to-ready hot path.
      Terminal states (completed prepare, unprepare) write a side slot
      (data only, NOT synced) and then the primary with fdatasync — the
      primary is the terminal store's sole durability point, so the hot
      path pays exactly one device sync per store. The unsynced side
      write keeps recovery fresh: if a LATER primary overwrite tears,
      load() falls back to the most recent durable slot (this side copy
      if it reached the device, else the previous intent record) rather
      than an older settled state; and load_or_init() rewrites a damaged
      primary at the next start. A tear in the side slot itself loses
      nothing — its envelope fails the checksum and the synced primary
      holds the identical state.
    - A downgraded driver that only knows the single-file layout reads
      the primary = the latest settled state. If it then writes its own
      rename-style (seq-less) checkpoints, load() treats such a legacy
      primary as authoritative over any leftover side slots from before
      the downgrade (the old driver's last word is the truth);
      load_or_init() migrates it into the slot scheme immediately.
    """

    SLOT_PAD = 4096

    def __init__(self, directory: str, filename: str = "checkpoint.json"):
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, filename)
        self._side_paths = (self._path + ".b", self._path + ".c")
        self._fds: Dict[str, int] = {}
        self._sizes: Dict[str, int] = {}
        # Observability counters (the group-commit regression tripwire,
        # hack/perf.sh): total store() calls, terminal (non-intent)
        # stores, and actual device syncs issued on slot data. A batch
        # of N claims must land exactly 1 terminal store = 1 slot sync;
        # N syncs here means the group commit silently degraded to
        # per-claim commits.
        self.stores: int = 0
        self.terminal_stores: int = 0
        self.slot_syncs: int = 0
        # Seed per-slot seqs from whatever is on disk so a manager that
        # stores before loading (e.g. a tool force-writing a downgrade
        # image) still supersedes stale slots from an earlier process,
        # and so side-slot ping-pong resumes on the older slot. Uses the
        # checksum-validating _load_slot: a corrupt slot seeds 0, sorting
        # it FIRST for overwrite — otherwise its stale-but-high seq would
        # steer the next store onto the last good side slot.
        self._slot_seqs: Dict[str, int] = {}
        for p in (self._path, *self._side_paths):
            r = self._load_slot(p)
            self._slot_seqs[p] = (r[0] or 0) if isinstance(r, tuple) else 0
        self._seq = max(self._slot_seqs.values())

    @property
    def path(self) -> str:
        return self._path

    def close(self) -> None:
        for fd in self._fds.values():
            try:
                vfs.close_fd(fd)
            except OSError:
                pass
        self._fds.clear()
        self._sizes.clear()

    def _write_slot(self, path: str, data: bytes, sync: bool = True) -> None:
        padded = data + b" " * (-len(data) % self.SLOT_PAD)
        fd = self._fds.get(path)
        if fd is None:
            existed = os.path.exists(path)
            fd = vfs.open_fd(path, os.O_RDWR | os.O_CREAT, 0o600)
            self._fds[path] = fd
            self._sizes[path] = os.fstat(fd).st_size
            if not existed:
                # Durable dirent for a NEW slot file: fdatasync persists
                # inode data, not the directory entry — without this a
                # post-crash reboot can show no file at all, losing the
                # store-before-side-effects guarantee. Once per file.
                vfs.fsync_dir(os.path.dirname(path))
        off = 0
        while off < len(padded):  # POSIX permits short writes
            n = vfs.pwrite(fd, padded[off:], off)
            if n <= 0:
                raise CheckpointError(f"short write to {path} at {off}")
            off += n
        if self._sizes[path] != len(padded):
            vfs.ftruncate(fd, len(padded))
            self._sizes[path] = len(padded)
        # Data-only sync: the durability point for the claim state machine
        # (store-before-side-effects). fdatasync is POSIX-but-not-macOS;
        # fall back to fsync there. sync=False callers (the terminal
        # store's side-slot copy) get durability from a later synced slot.
        if sync:
            vfs.fdatasync(fd)
            self.slot_syncs += 1

    def store(self, cp: Checkpoint, version: str = "v2",
              intent: bool = False) -> None:
        """Persist the full state. ``intent=True`` marks a transient
        mid-operation record (side slot only, one write); terminal stores
        write side-then-primary (see class doc for the crash analysis)."""
        # Injection site: store failure (ENOSPC, fsync EIO) — prepare and
        # unprepare must stay retryable/idempotent when the state machine
        # cannot persist.
        FAULTS.check("checkpoint.store", intent=intent)
        self.stores += 1
        if not intent:
            self.terminal_stores += 1
        doc = cp.to_v1_doc() if version == "v1" else cp.to_v2_doc()
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        self._seq += 1
        # Envelope assembled around the already-serialized payload (it is
        # the checksum's exact input, so embedding it verbatim both avoids
        # a second serialization and makes the checksum self-evidently
        # consistent). `seqsum` covers the seq, which sits outside the
        # data checksum (kept payload-only for legacy compatibility both
        # ways): without it, a seq mangled into a different valid integer
        # would silently reorder slot selection and could resurrect stale
        # state. Legacy readers ignore the unknown key.
        envelope = ('{"checksum": %d, "seq": %d, "seqsum": %d, "data": %s}'
                    % (zlib.crc32(payload.encode()), self._seq,
                       zlib.crc32(b"%d" % self._seq), payload)).encode()
        # Ping-pong: overwrite the STALER side slot, so the fresher one
        # still holds the previous state if this write tears.
        side = min(self._side_paths, key=lambda p: self._slot_seqs[p])
        # Intent stores sync the side slot (it is their durability point);
        # terminal stores leave it as a data-only recovery copy and sync
        # the primary below — one fdatasync either way (hot-path cost,
        # SURVEY §3.2).
        self._write_slot(side, envelope, sync=intent)
        self._slot_seqs[side] = self._seq
        if not intent:
            # In place, like the sides: the PrepareCompleted store IS on
            # the claim-to-ready path (a tmp+rename here measured +0.7ms
            # on p50). Residual risk accepted: a tear here leaves the
            # primary unparseable until the next driver start repairs it
            # (load_or_init) — only a crash followed by a downgrade to a
            # single-file-scheme binary WITHOUT an intervening new-driver
            # start ever surfaces it, and the side slots still hold the
            # full state for recovery.
            self._write_slot(self._path, envelope)
            self._slot_seqs[self._path] = self._seq
        # Injection site for torn writes: the armed action scribbles on
        # the just-written slot files; the next load must recover from
        # the surviving slots (crash-consistency chaos).
        FAULTS.check("checkpoint.corrupt",
                     paths=(side,) if intent else (side, self._path))

    def store_batch(self, cp: Checkpoint, *, present=(), absent=(),
                    version: str = "v2", intent: bool = False) -> None:
        """Multi-claim group commit: ONE slot write + ONE durable sync
        covering every claim the batch touched — N claims, 1 fdatasync,
        instead of the N the per-claim loop paid (SURVEY §9). The
        crash-consistency story is unchanged: the durable image is still
        the FULL state written through store(), so a crash before this
        call replays every member from its previous durable state and a
        crash after it finds every member settled together.

        `present`/`absent` are the commit's claim-level postconditions
        (uids the batch prepared / removed): a group commit whose
        in-memory state silently dropped a member — memory running ahead
        of or behind disk, the exact bug class chaos seed 5 found on the
        unprepare path — is refused here, before anything durable
        happens, instead of surfacing as a resurrected or lost claim at
        the next restart."""
        missing = [u for u in present if u not in cp.claims]
        lingering = [u for u in absent if u in cp.claims]
        if missing or lingering:
            raise CheckpointError(
                f"group commit inconsistent: missing={missing} "
                f"lingering={lingering}")
        self.store(cp, version=version, intent=intent)

    def _load_slot(self, path: str):
        """-> (seq | None-for-legacy, doc) or None (absent/empty) or
        'corrupt'. The doc is NOT deserialized into a Checkpoint here so
        version-compat policy stays in load()."""
        try:
            with open(path) as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        if not raw.strip():
            return None
        try:
            envelope = json.loads(raw)
        except json.JSONDecodeError:
            return "corrupt"
        doc = envelope.get("data") if isinstance(envelope, dict) else None
        if doc is None:
            return "corrupt"
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        if zlib.crc32(payload.encode()) != envelope.get("checksum"):
            return "corrupt"
        seq = envelope.get("seq")
        if seq is not None:
            # seq sits outside the checksum (which covers only `data`, for
            # legacy compatibility both ways): a mangled seq must degrade
            # to "corrupt slot", not crash slot selection.
            try:
                seq = int(seq)
            except (ValueError, TypeError):
                return "corrupt"
            # seqsum (when present — absent in pre-seqsum envelopes, whose
            # seq stays best-effort) catches a seq mangled into a DIFFERENT
            # valid integer, which would silently reorder slot selection.
            seqsum = envelope.get("seqsum")
            if seqsum is not None and seqsum != zlib.crc32(b"%d" % seq):
                return "corrupt"
        return seq, doc

    def load(self) -> Optional[Checkpoint]:
        """None when no checkpoint exists (first start). A *legacy*
        (seq-less, rename-scheme) primary is authoritative: it means a
        downgraded driver wrote last, and whatever side slots remain
        predate the downgrade. Otherwise the highest-seq valid slot
        wins. Raises only when every present slot is corrupt."""
        # (The __init__ seq seeding also parsed these slots; re-reading
        # here costs ~3 4KiB files once per process and keeps load()
        # correct after intervening stores — not worth a cache.)
        results = {p: self._load_slot(p)
                   for p in (self._path, *self._side_paths)}
        primary = results[self._path]
        if isinstance(primary, tuple) and primary[0] is None:
            return Checkpoint.from_doc(primary[1])
        valid = [r for r in results.values()
                 if isinstance(r, tuple) and r[0] is not None]
        if valid:
            seq, doc = max(valid, key=lambda r: r[0])
            self._seq = max(self._seq, seq)
            return Checkpoint.from_doc(doc)
        corrupt = [p for p, r in results.items() if r == "corrupt"]
        if corrupt:
            raise CheckpointError(
                f"checkpoint corrupt, no valid slot: {', '.join(corrupt)}")
        return None

    def load_or_init(self) -> Checkpoint:
        """Load at process start, initializing an empty checkpoint on
        first run — and ALWAYS re-storing what was loaded. The store
        repairs whatever the load tolerated (a slot torn by a crash, a
        stale loser slot) so the every-slot-valid invariant is restored
        before new in-place overwrites put it at risk again, and it
        migrates a legacy (seq-less, rename-scheme) primary into the
        slot scheme so a post-upgrade crash cannot out-rank newer intent
        records with the legacy file."""
        cp = self.load()
        if cp is None:
            cp = Checkpoint()
        # A PrepareStarted claim recovered here came from a crash mid-
        # prepare: persisting it terminally is the intended graduation to
        # a rollback record (same class as the failed-prepare store,
        # tpuplugin/device_state.py error path) — v2 readers on both
        # sides of an up/downgrade handle the state, and the v1 view
        # drops non-completed claims by construction (to_v1_doc).
        self.store(cp)
        return cp
