"""TPU kubelet plugin driver: DRA callbacks + publishing + health wiring.

Reference: cmd/gpu-kubelet-plugin/driver.go:49-301 — implements the
kubeletplugin callbacks, holds a per-node flock so two driver pods (rolling
upgrade) never interleave prepare/unprepare (:166-215), publishes
ResourceSlices (:217-235) and republishes on health events (:237-301).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from tpu_dra.infra import featuregates
from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.flock import Flock, SharedFlock
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.infra.trace import TRACEPARENT_ANNOTATION, TRACER
from tpu_dra.infra.workqueue import WorkQueue, default_prep_unprep_rate_limiter
from tpu_dra.k8s import ApiClient, RESOURCECLAIMS
from tpu_dra.k8s.client import NotFoundError
from tpu_dra.kubeletplugin.pipeline import RpcPipeline
from tpu_dra.kubeletplugin.server import (
    Claim, DRAPluginServer, DriverCallbacks, PrepareResult, publish_resources,
)
from tpu_dra.native.tpuinfo import HealthEvent, TpuInfoBackend
from tpu_dra.tpuplugin.device_state import DeviceState
from tpu_dra.tpuplugin.health import DeviceHealthMonitor, RECOVERED_KIND

log = logging.getLogger("tpu_dra.tpuplugin")

claim_prepare_seconds = DefaultRegistry.histogram(
    "tpu_dra_claim_prepare_seconds",
    "NodePrepareResources batch-amortized per-claim latency (batch wall / "
    "claims, observed once per claim; claims in a batch complete together, "
    "so individual tails live in the batch wall, not here)")

prepare_batch_size = DefaultRegistry.histogram(
    "tpu_dra_prepare_batch_size",
    "Claims per NodePrepareResources RPC (kubelet batches a pod's claims; "
    "the batch is the group-commit unit)",
    buckets=(1, 2, 4, 8, 16, 32, 64))

# Wire-breakdown components (SURVEY §14): the server-side share of
# prepare_breakdown_rpc_wire_ms, split so a wire regression names its
# stage — request decode (claim-list build), pipeline queueing
# (admission window + per-claim-set ordering), response encode.
_WIRE_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                 0.005, 0.01, 0.05)
wire_decode_seconds = DefaultRegistry.histogram(
    "tpu_dra_prepare_wire_decode_seconds",
    "server-side request-decode stage per prepare RPC",
    buckets=_WIRE_BUCKETS)
wire_queue_seconds = DefaultRegistry.histogram(
    "tpu_dra_prepare_wire_queue_seconds",
    "pipeline queue stage per prepare RPC: in-flight-window admission "
    "plus per-claim-set ordering waits",
    buckets=_WIRE_BUCKETS)
wire_encode_seconds = DefaultRegistry.histogram(
    "tpu_dra_prepare_wire_encode_seconds",
    "server-side response-encode stage per prepare RPC",
    buckets=_WIRE_BUCKETS)


class TpuDriver(DriverCallbacks):
    def __init__(self, *, state: DeviceState, client: ApiClient,
                 driver_name: str, node_name: str,
                 plugin_dir: str, registry_dir: Optional[str] = None,
                 flock_path: Optional[str] = None,
                 additional_codes_to_ignore=None):
        self._state = state
        self._client = client
        self._driver_name = driver_name
        self._node_name = node_name
        # Shared ownership over the node-global flock: the flock fences
        # OTHER processes (rolling upgrade); concurrent RPC threads of
        # this process share it so the pipeline can overlap them.
        self._pu_lock = SharedFlock(Flock(flock_path
                                          or f"{plugin_dir}/pu.lock"))
        # Pipelined admission: bounded in-flight window + per-claim-set
        # keyed ordering (two RPCs touching the same claim never
        # reorder; disjoint RPCs overlap — decode/fetch of RPC N+1 runs
        # while RPC N commits).
        self._pipeline = RpcPipeline()
        # Server-side wire attribution of the LAST prepare RPC
        # ({decode,queue,encode,handler} ms) — the bench's wire-split
        # source, paired with last_prepare_ms.
        self.last_wire_breakdown: Dict[str, float] = {}
        # Per-HANDLER-THREAD queue share: prepare_claims and the
        # server's record_wire callback run on the same gRPC handler
        # thread, and concurrent RPCs are real under the pipeline — a
        # shared field would pair RPC A's decode with RPC B's queue.
        self._wire_tls = threading.local()
        # Claim-fetch fan-out pool: a batch's ResourceClaims are fetched
        # concurrently so the API-server round-trip is paid once per RPC
        # wall-clock, not once per claim. Sized past any realistic
        # per-pod claim count; larger batches just wave through in turns.
        self._fetch_workers = 8
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=self._fetch_workers,
            thread_name_prefix="tpu-dra-claim-fetch")
        # Wall ms of the last prepare_claims batch (flock + claim fetch
        # + DeviceState.prepare_batch): with the client-observed latency
        # this attributes the gRPC wire share of claim-to-ready (bench).
        self.last_prepare_ms: float = 0.0
        self._pool_generation = 1
        self._gen_lock = threading.Lock()
        self.server = DRAPluginServer(
            driver_name=driver_name, node_name=node_name, callbacks=self,
            plugin_dir=plugin_dir, registry_dir=registry_dir)
        # Retry queue for ResourceSlice (re)publishing: a failed republish
        # after a health event must not strand a dead chip in the inventory
        # (closes the known gap the reference documents at driver.go:283-293).
        self._publish_queue = WorkQueue(default_prep_unprep_rate_limiter())
        # Set once the initial ResourceSlice publish lands (start()).
        self.first_published = threading.Event()
        self._health: Optional[DeviceHealthMonitor] = None
        if featuregates.enabled(featuregates.TPUDeviceHealthCheck):
            self._health = DeviceHealthMonitor(
                state.backend, self._on_unhealthy_event,
                additional_codes_to_ignore=additional_codes_to_ignore)

    # -- lifecycle ----------------------------------------------------------

    def start(self, publish_wait: float = 5.0) -> None:
        """Bring up the DRA socket, then run the initial ResourceSlice
        publish through the retry queue and gate kubelet REGISTRATION on
        its first success (Helper sequencing, driver.go:73-116): an API
        server blip at plugin start backs off instead of crashing the pod,
        and kubelet is not told about a driver whose inventory the
        scheduler cannot see yet.

        publish_wait: best-effort block for the first publish so callers
        observe the steady state; on timeout the queue keeps retrying in
        the background (0 to not wait).
        """
        self.server.start(register=False)
        self._publish_queue.run_in_thread()
        if self._health:
            self._health.start()
        self._publish_queue.enqueue(
            None, lambda _obj: self._publish_and_register(), key="publish")
        if publish_wait:
            self.first_published.wait(publish_wait)

    def shutdown(self, drain: bool = True) -> float:
        """Tear down in drain order (SURVEY §22 hot-restart protocol):
        stop admitting RPCs and wait out the in-flight pipeline
        (clients see a draining refusal and retry against the next
        incarnation), stop auxiliaries, stop the transports, then run
        the journal barrier so the next incarnation recovers a
        complete tail. Returns the drain window seconds (0.0 when
        drain=False — the crash-shaped teardown tests use)."""
        drain_s = self._pipeline.drain() if drain else 0.0
        if self._health:
            self._health.stop()
        self._publish_queue.shutdown()
        self.server.stop()
        self._fetch_pool.shutdown(wait=True)
        self._state.flush_journal()
        self._state.close()
        return drain_s

    # -- DRA callbacks ------------------------------------------------------

    def prepare_claims(self, claims: List[Claim]) -> Dict[str, PrepareResult]:
        """nodePrepareResource analog (driver.go:166-193), pipelined:
        the RPC is the unit of work, and concurrent RPCs overlap. The
        stages per RPC: admission (bounded in-flight window) ->
        concurrent ResourceClaim fetch fan-out (overlaps freely — reads
        the API server, not driver state) -> per-claim-set ordering
        (two RPCs touching the same claim never reorder) -> shared
        flock -> DeviceState group commit, whose journal fdatasync
        coalesces across whichever RPCs reach it together. Per-claim
        errors (404, UID mismatch, prepare failure) isolate to that
        claim's result."""
        t0 = time.monotonic()
        prepare_batch_size.observe(len(claims))
        results: Dict[str, PrepareResult] = {}
        try:
            ticket = self._pipeline.admit(c.uid for c in claims)
        except (TimeoutError, FaultInjected) as e:
            # Window never freed (wedged in-flight RPCs) or an injected
            # admission refusal (prepare.rpc_admit): fail fast so
            # kubelet retries instead of piling blocked handlers.
            return {c.uid: PrepareResult(error=str(e)) for c in claims}
        # uid -> the claim's rpc-level span: continues the trace the
        # scheduler stamped into the claim annotation (SURVEY §19) and
        # re-stamps its OWN traceparent before the state machine sees
        # the object, so every prepare.* span nests under rpc.prepare.
        rpc_spans: Dict[str, object] = {}
        try:
            objs = []
            for claim, (obj, err) in self._fetch_claims(claims):
                if err is not None:
                    results[claim.uid] = PrepareResult(error=err)
                    continue
                span = TRACER.begin(
                    "rpc.prepare", root=True,
                    traceparent=(obj["metadata"].get("annotations")
                                 or {}).get(TRACEPARENT_ANNOTATION),
                    attributes={"claim_uid": claim.uid})
                tp = span.traceparent()
                if tp:
                    obj["metadata"].setdefault(
                        "annotations", {})[TRACEPARENT_ANNOTATION] = tp
                rpc_spans[claim.uid] = span
                objs.append(obj)
            try:
                self._pipeline.order(ticket)
                self._pu_lock.acquire(timeout=10.0)
            except TimeoutError as e:
                for span in rpc_spans.values():
                    span.abandon(str(e))
                rpc_spans.clear()
                return {c.uid: PrepareResult(error=str(e))
                        for c in claims}
            try:
                if objs:
                    results.update(self._state.prepare_batch(objs))
            finally:
                self._pu_lock.release()
            elapsed = time.monotonic() - t0
            # Batch members complete together, so the honest per-claim
            # number is the amortized share (see the metric help text).
            per_claim = elapsed / max(len(claims), 1)
            for _ in claims:
                claim_prepare_seconds.observe(per_claim)
            self.last_prepare_ms = elapsed * 1e3
            self._wire_tls.queue_s = ticket.queue_s
            wire_queue_seconds.observe(ticket.queue_s)
            return results
        finally:
            for uid, span in rpc_spans.items():
                res = results.get(uid)
                if res is None:
                    span.abandon("no result recorded (handler error)")
                elif res.error:
                    span.abandon(res.error)
                else:
                    span.end()
            self._pipeline.done(ticket)

    def unprepare_claims(self, claims: List[Claim]) -> Dict[str, str]:
        """Same pipeline as prepare (shared claim-uid ordering — an
        unprepare never overtakes the prepare it follows), one
        group-committed unprepare per RPC."""
        try:
            ticket = self._pipeline.admit(c.uid for c in claims)
        except (TimeoutError, FaultInjected) as e:
            return {c.uid: str(e) for c in claims}
        try:
            try:
                self._pipeline.order(ticket)
                self._pu_lock.acquire(timeout=10.0)
            except TimeoutError as e:
                return {c.uid: str(e) for c in claims}
            try:
                errors = self._state.unprepare_batch(
                    [c.uid for c in claims])
                return {c.uid: errors.get(c.uid) or "" for c in claims}
            finally:
                self._pu_lock.release()
        finally:
            self._pipeline.done(ticket)

    def record_wire(self, stage_s: Dict[str, float]) -> None:
        """Per-RPC wire attribution from the gRPC handler (server.py):
        decode/encode/handler seconds, merged with the pipeline queue
        share measured here. The stage stopwatches are synthesized into
        ``rpc.<stage>`` spans and the bench's `last_wire_breakdown`
        keys are DERIVED from those spans (SURVEY §19: the span layer
        is the single source of truth for attribution; the stopwatch
        keys keep their byte-compatible names)."""
        queue_s = getattr(self._wire_tls, "queue_s", 0.0)
        self._wire_tls.queue_s = 0.0  # consumed: don't smear onto a
        # later RPC on this thread that timed out before measuring.
        spans = {
            stage: TRACER.record_span(f"rpc.{stage}", seconds)
            for stage, seconds in (
                ("decode", stage_s.get("decode", 0.0)),
                ("queue", queue_s),
                ("encode", stage_s.get("encode", 0.0)),
                ("handler", stage_s.get("handler", 0.0)))}
        wire_decode_seconds.observe(spans["decode"].duration_s)
        wire_encode_seconds.observe(spans["encode"].duration_s)
        self.last_wire_breakdown = {
            stage: span.duration_ms for stage, span in spans.items()}

    def _fetch_claims(self, claims: List[Claim]
                      ) -> List[Tuple[Claim, Tuple[Optional[Dict],
                                                   Optional[str]]]]:
        """Concurrent ResourceClaim fan-out: [(claim, (obj|None,
        err|None))], duplicates collapsed to their first occurrence.
        Single-claim batches fetch inline — pool dispatch buys nothing.
        Larger batches fan out as ONE CHUNK PER WORKER, not one task
        per claim: each task is a sequential loop over its chunk, so a
        64-claim batch costs 8 pool wakeups instead of 64 (sub-ms
        per-claim tasks thrash the executor instead of overlapping)
        while the API round-trips still run 8 wide."""
        unique: List[Claim] = []
        seen = set()
        for claim in claims:
            if claim.uid not in seen:
                seen.add(claim.uid)
                unique.append(claim)
        if len(unique) == 1:
            return [(unique[0], self._fetch_one(unique[0]))]
        n_chunks = min(self._fetch_workers, len(unique))
        chunks = [unique[i::n_chunks] for i in range(n_chunks)]

        def fetch_chunk(chunk):
            return [self._fetch_one(c) for c in chunk]

        futures = [self._fetch_pool.submit(fetch_chunk, ch)
                   for ch in chunks]
        by_uid = {}
        for ch, f in zip(chunks, futures):
            for claim, res in zip(ch, f.result()):
                by_uid[claim.uid] = res
        return [(c, by_uid[c.uid]) for c in unique]

    def _fetch_one(self, claim: Claim
                   ) -> Tuple[Optional[Dict], Optional[str]]:
        """(ResourceClaim, None) or (None, error). Never raises: one
        failed fetch must not take down its batch siblings."""
        try:
            # Injection site: a single claim's fetch fails while the
            # rest of the batch proceeds (error-isolation chaos).
            FAULTS.check("prepare.batch_fetch", claim_uid=claim.uid)
            obj = self._client.get(RESOURCECLAIMS, claim.name,
                                   claim.namespace)
        except NotFoundError:
            return None, (f"resourceclaim {claim.namespace}/{claim.name} "
                          "not found")
        except Exception as e:  # noqa: BLE001 — isolate to this claim
            return None, f"fetch resourceclaim: {e}"
        if obj["metadata"].get("uid") != claim.uid:
            return None, f"claim UID mismatch for {claim.namespace}/{claim.name}"
        return obj, None

    # -- publishing ---------------------------------------------------------

    def publish_resources(self) -> None:
        with self._gen_lock:
            devices = self._state.healthy_devices()
            publish_resources(self._client, self._driver_name, self._node_name,
                              devices, pool_generation=self._pool_generation)
            self._pool_generation += 1

    def _publish_and_register(self) -> None:
        """Single callback behind the "publish" queue key: every enqueue —
        startup AND health republish — goes through here, because the
        queue's latest-wins semantics would otherwise let a health event
        supersede a still-retrying startup publish and silently drop the
        registration gate."""
        self.publish_resources()
        if not self.first_published.is_set():
            self.server.start_registration()
            self.first_published.set()

    # -- health -------------------------------------------------------------

    def _on_unhealthy_event(self, event: HealthEvent) -> None:
        """deviceHealthEvents analog (driver.go:237-301): yank affected
        devices and republish through the retry queue — a failed republish
        is retried with backoff rather than dropped (the reference documents
        the no-retry behavior as a known gap, driver.go:283-293).

        Improvement over the reference: an explicit `recovered` record in
        the accel health stream re-admits the chip and republishes — the
        reference requires a driver restart to re-add a yanked GPU
        (driver.go:263-264)."""
        recovered = event.kind == RECOVERED_KIND
        mark = (self._state.mark_healthy if recovered
                else self._state.mark_unhealthy)
        if event.chip_index >= 0:
            affected = mark(event.chip_index)
        else:
            # chip_index < 0 addresses all chips (board-level record).
            affected = []
            for index in self._state.chip_indices():
                affected += mark(index)
        if recovered:
            if not affected:
                # Never yanked — or QUARANTINED: the ladder holds the
                # chip out of the inventory through recovery events
                # (ping-pong is what graduated it); nothing to republish.
                return
            log.info("health recovery (%s): re-admitting devices %s",
                     "all chips" if event.chip_index < 0
                     else f"chip {event.chip_index}", affected)
        else:
            log.warning("health event %s (code %d): yanking devices %s",
                        event.kind, event.code, affected)
        self._publish_queue.enqueue(
            None, lambda _obj: self._publish_and_register(), key="publish")

    def clear_quarantine(self, chip_index: Optional[int] = None) -> List[str]:
        """Operator seam: lift chip quarantine (None = all) and republish
        the re-admitted devices through the retry queue. Returns the
        re-admitted device names."""
        affected = self._state.clear_quarantine(chip_index)
        if affected:
            log.info("quarantine cleared (%s): re-admitting devices %s",
                     "all chips" if chip_index is None
                     else f"chip {chip_index}", affected)
            self._publish_queue.enqueue(
                None, lambda _obj: self._publish_and_register(),
                key="publish")
        return affected
