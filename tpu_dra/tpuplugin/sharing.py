"""Sharing managers: time-slicing and multiprocess.

Reference: cmd/gpu-kubelet-plugin/sharing.go:60-451 —

- ``TimeSlicingManager`` execs ``nvidia-smi compute-policy --set-timeslice``
  and resets compute mode (sharing.go:60-126, nvlib.go:564-601). TPU: the
  accel driver's program scheduler quantum, programmed per chip through
  libtpuinfo (or the ``tpuctl`` exec seam — both supported; exec keeps the
  audit trail, direct lib call avoids the fork).
- ``MpsManager`` runs a per-claim MPS control daemon as a Deployment with
  tmpfs /dev/shm + pipe dir, waits for readiness, and contributes CDI edits
  (sharing.go:163-451). TPU analog ``MultiprocessManager``: concurrent
  libtpu processes on one chip need a per-claim coordination directory and
  premapped-HBM/core limits exported as env; the Deployment-per-claim
  lifecycle (create → assert ready → CDI edits → stop) is preserved so
  operators get the same operational surface.
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import time
from typing import Dict, List, Optional

from tpu_dra.api import types as apitypes
from tpu_dra.k8s import ApiClient, DEPLOYMENTS, new_object_meta
from tpu_dra.k8s.client import AlreadyExistsError, ConflictError, NotFoundError
from tpu_dra.native.tpuinfo import Chip, TpuInfoBackend

log = logging.getLogger("tpu_dra.sharing")


class TimeSlicingManager:
    """Programs per-chip time-slice quanta (SetTimeSlice analog)."""

    def __init__(self, backend: TpuInfoBackend, tpuctl_path: Optional[str] = None,
                 sysfs_root: str = ""):
        self._backend = backend
        self._tpuctl = tpuctl_path
        self._sysfs_root = sysfs_root

    def set_timeslice(self, chips: List[Chip],
                      config: apitypes.TimeSlicingConfig) -> None:
        interval_us = config.interval_us()
        for chip in chips:
            if self._tpuctl:
                env = dict(os.environ)
                if self._sysfs_root:
                    env["TPUINFO_SYSFS_ROOT"] = self._sysfs_root
                res = subprocess.run(
                    [self._tpuctl, "set-timeslice", str(chip.index),
                     str(interval_us)],
                    env=env, capture_output=True, text=True)
                if res.returncode != 0:
                    raise RuntimeError(
                        f"tpuctl set-timeslice chip {chip.index}: {res.stderr.strip()}")
            else:
                self._backend.set_timeslice(chip.index, interval_us)
            # Time-slicing implies shared access: drop exclusive mode
            # (the compute-mode DEFAULT reset, nvlib.go:585-599).
            self._backend.set_exclusive_mode(chip.index, False)

    def reset(self, chips: List[Chip]) -> None:
        self.set_timeslice(chips, apitypes.TimeSlicingConfig("Default"))


class MultiprocessDaemon:
    """Per-claim multiprocess coordination daemon (MpsControlDaemon analog,
    sharing.go:191-412): owns the claim's coordination directory and the
    Deployment that runs the coordinator pod on this node."""

    def __init__(self, claim_uid: str, chips: List[Chip],
                 config: apitypes.MultiprocessConfig, *,
                 node_name: str, namespace: str, root_dir: str,
                 client: ApiClient, image: str):
        self._claim_uid = claim_uid
        self._chips = chips
        self._config = config
        self._node_name = node_name
        self._namespace = namespace
        self._dir = os.path.join(root_dir, claim_uid)
        self._client = client
        self._image = image
        self._name = f"tpu-multiprocess-{claim_uid[:13]}"

    @property
    def deployment_name(self) -> str:
        return self._name

    def _limits(self) -> Dict[str, int]:
        """Per-chip premapped-HBM caps (bytes by uuid) — the single source
        both the coordinator args and the CDI env are rendered from, so the
        arbiter's limits.env and the tenants' environment always agree."""
        uuids = [c.uuid for c in self._chips]
        indices = {c.uuid: c.index for c in self._chips}
        if self._config.per_device_hbm_limit is not None:
            return self._config.per_device_hbm_limit.normalize(
                uuids, indices, self._config.default_hbm_limit)
        if self._config.default_hbm_limit is not None:
            from tpu_dra.infra.quantity import Quantity
            return {u: Quantity(self._config.default_hbm_limit).value
                    for u in uuids}
        return {}

    def _coordinator_command(self) -> List[str]:
        """The container command: the real tpu-multiprocess-coordinator
        binary (native/src/multiprocess_coordinator.cc) with this claim's
        chips and limits. Mirrors how the reference renders MPS settings
        into the control daemon's startup script
        (templates/mps-control-daemon.tmpl.yaml:27-42)."""
        cmd = ["tpu-multiprocess-coordinator", "--dir", "/multiprocess",
               "--chips", ",".join(str(c.index) for c in self._chips)]
        limits = self._limits()
        if limits:
            cmd += ["--hbm-limit-map",
                    ",".join(f"{u}={b}" for u, b in sorted(limits.items()))]
        if self._config.default_active_cores_percentage is not None:
            cmd += ["--tensorcore-pct",
                    str(self._config.default_active_cores_percentage)]
        return cmd

    def start(self) -> None:
        """Create coordination dir + Deployment (Start analog,
        sharing.go:191-296)."""
        os.makedirs(os.path.join(self._dir, "pipe"), exist_ok=True)
        os.makedirs(os.path.join(self._dir, "log"), exist_ok=True)
        probe = {"exec": {"command": [
            "tpu-multiprocess-coordinator", "--check",
            "--dir", "/multiprocess"]}}
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": new_object_meta(
                self._name, self._namespace,
                labels={"app.kubernetes.io/name": "tpu-multiprocess-daemon",
                        "tpu.dev/claim-uid": self._claim_uid}),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"tpu.dev/claim-uid": self._claim_uid}},
                "template": {
                    "metadata": {"labels": {
                        "app.kubernetes.io/name": "tpu-multiprocess-daemon",
                        "tpu.dev/claim-uid": self._claim_uid}},
                    "spec": {
                        "nodeName": self._node_name,
                        "containers": [{
                            "name": "coordinator",
                            "image": self._image,
                            "command": self._coordinator_command(),
                            "env": [
                                {"name": "TPU_VISIBLE_CHIPS", "value": ",".join(
                                    str(c.index) for c in self._chips)},
                                {"name": "TPU_MULTIPROCESS_DIR",
                                 "value": "/multiprocess"},
                            ],
                            # Readiness comes from the binary's own probe
                            # (socket answers READY), the startup.log-based
                            # startupProbe shape of the reference template.
                            "startupProbe": {**probe,
                                             "initialDelaySeconds": 1,
                                             "periodSeconds": 1,
                                             "failureThreshold": 30},
                            "readinessProbe": {**probe, "periodSeconds": 5},
                            "volumeMounts": [
                                {"name": "coord", "mountPath": "/multiprocess"},
                                {"name": "shm", "mountPath": "/dev/shm"},
                            ],
                        }],
                        "volumes": [
                            {"name": "coord",
                             "hostPath": {"path": self._dir,
                                          "type": "DirectoryOrCreate"}},
                            {"name": "shm",
                             "emptyDir": {"medium": "Memory",
                                          "sizeLimit": "64Mi"}},
                        ],
                    },
                },
            },
        }
        try:
            self._client.create(DEPLOYMENTS, deployment)
        except (AlreadyExistsError, ConflictError):
            pass  # idempotent re-prepare after a crashed attempt

    def assert_ready(self, timeout: float = 30.0, interval: float = 0.2) -> None:
        """Block until the coordinator Deployment reports a ready replica
        (AssertReady analog, sharing.go:298-353)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                dep = self._client.get(DEPLOYMENTS, self._name, self._namespace)
            except NotFoundError:
                dep = None
            if dep and (dep.get("status") or {}).get("readyReplicas", 0) >= 1:
                return
            time.sleep(interval)
        raise TimeoutError(
            f"multiprocess daemon {self._name} not ready within {timeout}s")

    def cdi_edits(self) -> Dict:
        """Claim CDI contributions (GetCDIContainerEdits analog,
        sharing.go:355-375): coordination dir mount + limit env. The pipe
        path is the CUDA_MPS_PIPE_DIRECTORY analog — tenants find the
        coordinator's Unix socket there to register their lease."""
        env = {"TPU_MULTIPROCESS_DIR": "/multiprocess",
               "TPU_MULTIPROCESS_PIPE": "/multiprocess/pipe",
               "TPU_MULTIPROCESS_ID": self._claim_uid}
        if self._config.default_active_cores_percentage is not None:
            env["TPU_TENSORCORE_PERCENTAGE"] = str(
                self._config.default_active_cores_percentage)
        limits = self._limits()
        if limits:
            # libtpu reads a single per-process premapped-HBM cap; export the
            # per-chip map for multi-chip claims plus the scalar for 1-chip.
            env["TPU_HBM_LIMIT_MAP"] = ",".join(
                f"{u}={b}" for u, b in sorted(limits.items()))
            if len(limits) == 1:
                env["TPU_HBM_LIMIT_BYTES"] = str(next(iter(limits.values())))
        mounts = [{"hostPath": self._dir, "containerPath": "/multiprocess",
                   "options": ["rw", "nosuid", "nodev", "bind"]}]
        return {"env": env, "mounts": mounts}

    def stop(self) -> None:
        """Delete Deployment + coordination dir (Stop analog,
        sharing.go:377-412)."""
        self._client.delete(DEPLOYMENTS, self._name, self._namespace)
        shutil.rmtree(self._dir, ignore_errors=True)


class MultiprocessManager:
    """Factory/lifecycle tracking for per-claim daemons (MpsManager analog)."""

    def __init__(self, backend: TpuInfoBackend, client: ApiClient, *,
                 node_name: str, namespace: str, root_dir: str,
                 image: str = "tpu-dra-driver:latest",
                 ready_timeout: float = 30.0):
        self._backend = backend
        self._client = client
        self._node_name = node_name
        self._namespace = namespace
        self._root_dir = root_dir
        self._image = image
        self._ready_timeout = ready_timeout

    def daemon(self, claim_uid: str, chips: List[Chip],
               config: apitypes.MultiprocessConfig) -> MultiprocessDaemon:
        return MultiprocessDaemon(
            claim_uid, chips, config, node_name=self._node_name,
            namespace=self._namespace, root_dir=self._root_dir,
            client=self._client, image=self._image)

    def start(self, claim_uid: str, chips: List[Chip],
              config: apitypes.MultiprocessConfig,
              ready_timeout: Optional[float] = None) -> MultiprocessDaemon:
        # Multiprocess tenants must not race other workloads on the chip:
        # set exclusive-to-claim mode (EXCLUSIVE_PROCESS analog).
        for chip in chips:
            self._backend.set_exclusive_mode(chip.index, True)
        d = self.daemon(claim_uid, chips, config)
        d.start()
        d.assert_ready(timeout=ready_timeout if ready_timeout is not None
                       else self._ready_timeout)
        return d

    def stop(self, claim_uid: str, chips: List[Chip]) -> None:
        d = self.daemon(claim_uid, chips, apitypes.MultiprocessConfig())
        d.stop()
        for chip in chips:
            try:
                self._backend.set_exclusive_mode(chip.index, False)
            except Exception as e:  # noqa: BLE001 — chip may be gone
                # Visible, not fatal: a vanished chip cannot have its
                # mode cleared, but a HEALTHY chip left exclusive would
                # silently refuse the next shared claim.
                log.warning("clearing exclusive mode on chip %d "
                            "failed: %s", chip.index, e)
