"""Event-driven device health monitor.

Reference: cmd/gpu-kubelet-plugin/device_health.go:36-342 — registers for
NVML Xid-critical/ECC events, waits in a 5s-timeout loop, filters a skip
list of benign Xids (13,31,43,45,68,109 + flag-provided extras), maps the
event to devices and pushes them onto an `unhealthy` channel consumed by
the driver, which republishes the ResourceSlice without them (§3.5).

TPU translation: libtpuinfo tails the accel driver's health event stream.
Benign event codes are skipped by the same mechanism
(ADDITIONAL_CODES_TO_IGNORE flag analog of ADDITIONAL_XIDS_TO_IGNORE).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterable, Optional, Set

from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.infra.trace import dump_flight_recorder
from tpu_dra.native.tpuinfo import HealthEvent, TpuInfoBackend

log = logging.getLogger("tpu_dra.tpuplugin.health")

# 1 while a monitor thread is wedged in the backend event wait (stop()
# timed out joining it): health events are NOT flowing and chips can die
# unnoticed until restart. Previously a bare attribute nobody exported —
# an operator watching dashboards had no way to tell a dead health
# pipeline from a quiet one.
wedged_gauge = DefaultRegistry.gauge(
    "tpu_dra_health_monitor_wedged",
    "1 while the device health monitor thread is wedged in a backend "
    "wait that never returned (health events not flowing), 0 otherwise")

# Benign/app-level event codes that must not yank a chip (the Xid skip-list
# analog, device_health.go:320-342). Codes model: <100 = app/driver-level
# recoverable (program aborts, preemptions, watchdog restarts), >=100 =
# hardware faults — hardware-fault-range codes are never skipped by default.
DEFAULT_SKIPPED_CODES = frozenset({13, 31, 43, 45, 68})

# Event kind signalling a previously-faulted chip is serviceable again;
# the driver re-admits it to the inventory (a capability the reference
# lacks: restart required, driver.go:263-264).
RECOVERED_KIND = "recovered"

# The reference waits 5s per NVML eventSet.Wait iteration; we use a shorter
# quantum so stop() is responsive — the loop re-enters the wait immediately,
# so event latency is unchanged.
WAIT_TIMEOUT_S = 0.5


class DeviceHealthMonitor:
    def __init__(self, backend: TpuInfoBackend,
                 on_unhealthy: Callable[[HealthEvent], None],
                 additional_codes_to_ignore: Optional[Iterable[int]] = None):
        self._backend = backend
        self._on_unhealthy = on_unhealthy
        self._skip: Set[int] = set(DEFAULT_SKIPPED_CODES)
        self._skip.update(additional_codes_to_ignore or [])
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # True when stop() timed out joining the monitor thread: the
        # thread is wedged (a backend wait that never returns) and health
        # events are no longer flowing. Owners (shutdown paths, tests)
        # can assert on it; a silent return here previously made a dead
        # health pipeline indistinguishable from a clean stop.
        self.wedged = False

    def start(self) -> None:
        # A (re)started monitor clears the tripwire: the gauge reports
        # the CURRENT pipeline, not a predecessor a restart replaced.
        self.wedged = False
        wedged_gauge.set(0)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpu-health-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=WAIT_TIMEOUT_S + 1)
            if self._thread.is_alive():
                self.wedged = True
                wedged_gauge.set(1)
                # Flight-recorder dump trigger (SURVEY §19): the wedge
                # ships its evidence — recent spans, fault firings and
                # queue events around the moment the pipeline died.
                dump_path = dump_flight_recorder("wedged")
                log.error(
                    "health monitor thread did not stop within %.1fs — "
                    "wedged in the backend event wait; health events are "
                    "NOT flowing (chips can die unnoticed until "
                    "restart); flight recorder dumped to %s",
                    WAIT_TIMEOUT_S + 1, dump_path)

    def _run(self) -> None:
        """The eventSet.Wait loop (device_health.go:146-204)."""
        while not self._stop.is_set():
            # Injection site: chaos schedules mint synthetic events
            # (arm with payload=HealthEvent(...)) without a backend that
            # can produce them on demand.
            event = (FAULTS.pull("health.chip_event")
                     or self._backend.wait_health_event(WAIT_TIMEOUT_S))
            if event is None:
                continue
            # The skip list exists to stop benign codes from YANKING
            # chips; recovery records must never be filtered by it (a
            # swallowed recovery strands the chip out of the inventory).
            if event.kind != RECOVERED_KIND and event.code in self._skip:
                continue
            self._on_unhealthy(event)
