"""Node device state: checkpointed, idempotent Prepare/Unprepare.

Reference: cmd/gpu-kubelet-plugin/device_state.go —
``Prepare`` (:147-216): checkpoint-read for idempotency, write
PrepareStarted, prepare devices, write claim CDI spec, write
PrepareCompleted. ``Unprepare`` (:218-273) reverses it.
``prepareDevices`` (:302-469) resolves opaque configs with precedence
(class < claim, later-in-list > earlier, device-specific > catch-all),
normalizes/validates them, groups allocation results per config and applies
sharing (``applySharingConfig`` :567-615).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from contextlib import ExitStack
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu_dra.api import scheme as apischeme
from tpu_dra.api import types as apitypes
from tpu_dra.cdi.handler import CDIHandler, visible_chips_env
from tpu_dra.infra import featuregates, vfs
from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.infra.trace import (
    ENV_TRACEPARENT, TRACEPARENT_ANNOTATION, TRACER,
)
from tpu_dra.kubeletplugin.server import PreparedDevice, PrepareResult
from tpu_dra.native.tpuinfo import Chip, TpuInfoBackend
from tpu_dra.tpuplugin import deviceinfo
from tpu_dra.tpuplugin.checkpoint import (
    Checkpoint, CheckpointManager, PREPARE_COMPLETED, PREPARE_STARTED,
    PreparedClaim,
)
from tpu_dra.tpuplugin.passthrough import PassthroughManager
from tpu_dra.tpuplugin.sharing import MultiprocessManager, TimeSlicingManager
from tpu_dra.topology import mesh as topology_mesh
from tpu_dra.topology.meshexport import export_topology_env


log = logging.getLogger("tpu_dra.tpuplugin")

quarantined_chips_gauge = DefaultRegistry.gauge(
    "tpu_dra_quarantined_chips",
    "chips currently quarantined by the flap ladder on this node "
    "(excluded from every ResourceSlice publish until an operator "
    "clear or TTL expiry re-admits them; persisted in the checkpoint "
    "journal so the count survives restarts)")


class PrepareError(Exception):
    pass


def _config_compatible(cfg: object, dev_type: str) -> bool:
    if isinstance(cfg, apitypes.SubsliceConfig):
        return dev_type == deviceinfo.DEVICE_TYPE_SUBSLICE
    if isinstance(cfg, (apitypes.TpuConfig, apitypes.PassthroughConfig)):
        return dev_type == deviceinfo.DEVICE_TYPE_CHIP
    return False


def _core_ranges(cores: set) -> str:
    """Render a core index set as merged 'a-b' ranges: {0,1,3} -> '0-1,3-3'."""
    out = []
    run_start = prev = None
    for c in sorted(cores):
        if prev is None:
            run_start = prev = c
        elif c == prev + 1:
            prev = c
        else:
            out.append(f"{run_start}-{prev}")
            run_start = prev = c
    if prev is not None:
        out.append(f"{run_start}-{prev}")
    return ",".join(out)


def _prepared_device_from_record(record: Dict) -> PreparedDevice:
    """Rehydrate the kubelet-facing device from a checkpoint record."""
    return PreparedDevice(
        pool_name=record.get("pool", ""),
        device_name=record.get("device", ""),
        cdi_device_ids=list(record.get("cdi_ids") or []),
        request_names=[record["request"]] if record.get("request") else [])


@dataclass
class _ConfigResult:
    """One opaque config + the allocation results it applies to
    (the configResultsMap of prepareDevices :337-380)."""
    config: object
    source: str  # FromClass | FromClaim | default
    results: List[Dict] = field(default_factory=list)


@dataclass
class _BatchClaim:
    """One non-idempotent member of a prepare batch, carried from the
    pure phase through parallel apply to the group commit."""
    uid: str
    claim: Dict
    config_results: List[_ConfigResult]
    records: List[Dict]
    hazardous: bool = False    # needs the durable intent store
    serialize: bool = False    # side effects span beyond own chips
    slow_apply: bool = False   # apply blocks (exec / API round trips)
    timings: Dict[str, float] = field(default_factory=dict)
    error: Optional[str] = None
    # Serialized-but-unwritten claim spec (path, text), produced by the
    # apply phase; the batch submits ONE writer task for all members
    # (sub-ms tasks fanned out per-member thrash the GIL instead of
    # overlapping — measured 7x slower than a single sequential task).
    cdi_spec: Optional[tuple] = None
    # The batch's shared in-flight spec-write future (None once awaited
    # or when specs were written synchronously). The commit barrier
    # awaits it before any result externalizes.
    cdi_future: Optional[object] = None
    # The member's prepare.claim span (SURVEY §19): continues the
    # trace the RPC layer stamped into the claim annotation; its
    # children (prepare.sharing/guards/cdi*/journal) ARE the timings —
    # the `timings` dict above is derived from their durations.
    span: Optional[object] = None


class DeviceState:
    def __init__(self, *, backend: TpuInfoBackend, cdi: CDIHandler,
                 checkpoints: CheckpointManager, driver_name: str,
                 node_name: str,
                 ts_manager: Optional[TimeSlicingManager] = None,
                 mp_manager: Optional[MultiprocessManager] = None,
                 pt_manager: Optional[PassthroughManager] = None,
                 include_subslices: bool = True,
                 async_cdi: bool = True,
                 quarantine_threshold: int = 3,
                 quarantine_window_s: float = 60.0,
                 quarantine_ttl_s: float = 0.0):
        self._backend = backend
        self._cdi = cdi
        self._ckpt_mgr = checkpoints
        self._driver_name = driver_name
        self._node_name = node_name
        self._ts_manager = ts_manager
        self._mp_manager = mp_manager
        self._pt_manager = pt_manager
        self._lock = threading.Lock()
        chips = backend.chips()
        # Publish-time fabric validation: duplicate or out-of-bounds chip
        # coordinates mean the inventory lies about the ICI mesh — every
        # topology-scored placement downstream would be wrong. Reject
        # before anything reaches a ResourceSlice.
        topology_mesh.validate_chips(chips)
        self.allocatable = deviceinfo.enumerate_allocatable(
            chips, include_subslices=include_subslices)
        self._unhealthy_uuids: set = set()  # GUARDED_BY: _lock
        # Quarantine ladder (SURVEY §18): a chip whose unhealthy
        # TRANSITIONS (flaps — each one requires an intervening
        # recovery) reach `quarantine_threshold` within
        # `quarantine_window_s` graduates from transient-unhealthy to
        # quarantined: excluded from publish until an operator clear or
        # TTL expiry (`quarantine_ttl_s`; 0 = operator-only), and
        # persisted in the checkpoint journal so a plugin crash cannot
        # launder a flapping chip back into the scheduler's inventory.
        self._q_threshold = max(1, int(quarantine_threshold))
        self._q_window_s = float(quarantine_window_s)
        self._q_ttl_s = float(quarantine_ttl_s)
        # chip uuid -> monotonic timestamps of recent flaps (transient,
        # deliberately NOT persisted: the quarantine decision is; a
        # restart resets the window, which only delays re-graduation).
        self._flap_history: Dict[str, deque] = {}  # GUARDED_BY: _lock
        # Per-phase ms of the last non-idempotent prepare (see prepare()).
        self.last_prepare_breakdown: Dict[str, float] = {}
        # Batch-level phase ms of the last fully-successful prepare_batch
        # (decode, checkpoint_start, apply, checkpoint_final, total,
        # n_claims) — the bench's batch-path attribution source.
        self.last_batch_breakdown: Dict[str, float] = {}
        # Disjoint-chip parallel apply: side effects are chip-scoped
        # (time slices, exclusive mode, per-claim CDI files and
        # coordinator Deployments), so batch members touching disjoint
        # chip sets apply concurrently; members sharing a chip
        # serialize on its lock. Passthrough and unknown config kinds
        # additionally serialize on _hazard_lock: their side effects
        # (IOMMU-group rebinds) span beyond the claim's own chips.
        self._chip_locks: Dict[int, threading.Lock] = {
            c.index: threading.Lock() for c in backend.chips()}
        self._hazard_lock = threading.Lock()
        self._apply_pool: Optional[ThreadPoolExecutor] = None
        # Async claim-spec writer pool (SURVEY §14): spec tmp-write +
        # rename overlap the terminal checkpoint append + group sync;
        # the commit barrier (_await_cdi) runs before any result
        # externalizes. Disabled per-batch while a drmc vfs recorder is
        # installed — the crash enumerator needs a deterministic
        # durable-op sequence, and the sync fallback exercises the same
        # crash windows (a never-dir-synced rename is lost in the clean
        # image either way).
        # (Constructed eagerly — worker threads only materialize on the
        # first submit, so an unused pool costs nothing.)
        self._cdi_pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=4,
                               thread_name_prefix="tpu-dra-cdi-write")
            if async_cdi else None)
        # Standard per-node CDI spec is written once at startup
        # (NewDeviceState analog, device_state.go:59-145).
        self._cdi.create_standard_device_spec_file(backend.chips())
        self._checkpoint = self._ckpt_mgr.load_or_init()
        # Quarantine survives the restart (it was loaded with the
        # checkpoint); records for uuids no longer on this node (chip
        # physically replaced) are pruned — the replacement hardware
        # earns its own health record. The prune is in-memory only: it
        # persists with the next quarantine transition or compaction.
        known_uuids = {c.uuid for c in chips}
        for uuid in list(self._checkpoint.quarantine):
            if uuid not in known_uuids:
                log.info("dropping quarantine record for replaced chip "
                         "uuid %s", uuid)
                self._checkpoint.quarantine.pop(uuid, None)
        quarantined_chips_gauge.set(len(self._checkpoint.quarantine))
        # Orphan claim-spec GC: non-hazardous prepares (no side effects
        # beyond the CDI spec) skip the intent store, so a crash between
        # their CDI write and terminal checkpoint store leaves a spec file
        # for a claim the checkpoint never learned about. Reconcile here.
        for uid in self._cdi.list_claim_uids():
            if uid not in self._checkpoint.claims:
                self._cdi.delete_claim_spec_file(uid)
        # Orphan time-slice reconciliation: time-slicing prepares also
        # skip the intent store (see _config_hazard) — a crash between
        # set_timeslice and the terminal store leaves a chip-level
        # setting with no claim. Reset every chip NOT held by ANY
        # checkpointed claim to the driver default (one tpuctl exec per
        # free chip, once per process start; idempotent for untouched
        # chips). Chips of ANY live claim are excluded — not just
        # time-slicing ones — because reset() also clears exclusive
        # mode, which passthrough/multiprocess claims rely on (and a
        # VFIO-rebound passthrough chip has no accel fd to set a slice
        # on at all).
        if self._ts_manager is not None:
            held = {record.get("chip_index")
                    for prepared in self._checkpoint.claims.values()
                    for record in prepared.devices}
            for c in backend.chips():
                if c.index in held:
                    continue
                try:
                    self._ts_manager.reset([c])
                except Exception:  # noqa: BLE001 — one bad chip (still
                    # VFIO-rebound, hardware-faulted) must not crash-loop
                    # the plugin and take the whole node's chips with it.
                    log.warning("startup time-slice reset failed for "
                                "chip %d (continuing)", c.index,
                                exc_info=True)

    def flush_journal(self) -> None:
        """Settle every appended journal record to disk — the clean
        journal barrier the hot-restart drain runs before close()
        (SURVEY §22), so the next incarnation recovers a complete tail."""
        self._ckpt_mgr.journal_flush()

    def close(self) -> None:
        """Release cached checkpoint slot fds. The manager assumes a
        single writer per process; call this at driver shutdown (and from
        test fixtures that create many states)."""
        if self._apply_pool is not None:
            self._apply_pool.shutdown(wait=True)
            self._apply_pool = None
        if self._cdi_pool is not None:
            self._cdi_pool.shutdown(wait=True)
            self._cdi_pool = None
        self._ckpt_mgr.close()

    @property
    def backend(self):
        """The chip-info backend (read-only seam for collaborators that
        genuinely need hardware access, e.g. the health monitor — the
        driver must not reach into _backend)."""
        return self._backend

    def chip_indices(self) -> List[int]:
        """Indices of all chips on this node (board-level health events
        address every chip; the driver must not reach into _backend)."""
        return [c.index for c in self._backend.chips()]

    # ------------------------------------------------------------------
    # Prepare
    # ------------------------------------------------------------------

    def prepare(self, claim: Dict) -> PrepareResult:
        """claim: a resource.k8s.io/v1 ResourceClaim object (dict).

        Single-claim surface kept for recovery paths and tests; kubelet
        RPCs go through prepare_batch — this is a batch of one."""
        return self.prepare_batch([claim])[claim["metadata"]["uid"]]

    def prepare_batch(self, claims: List[Dict]) -> Dict[str, PrepareResult]:
        """Prepare every claim of one NodePrepareResources RPC as ONE
        unit of work (SURVEY §9): the pure phase and checkpoint mutation
        run under the global lock, side effects apply concurrently for
        disjoint-chip members, and durable state lands in group commits
        — one intent store covering all hazardous members, one terminal
        store for the whole batch (N claims, 1 fdatasync, instead of the
        N the per-claim loop paid).

        Per-claim transactional semantics are unchanged: a member that
        fails mid-apply unwinds itself (side effects reversed, CDI spec
        deleted, no checkpoint entry) while the survivors commit; errors
        isolate to the failing claim's result.

        Per-phase wall times of the last fully-successful batch land in
        `last_batch_breakdown`; single-claim batches additionally keep
        the historical `last_prepare_breakdown` (VERDICT r3: the r2->r3
        regression was never attributed). Both dicts are DERIVED from
        the span layer (SURVEY §19): every phase below is a span, the
        byte-compatible stopwatch keys are the spans' durations, and
        the try/finally here guarantees no span outlives the batch —
        a crash point anywhere inside leaves only closed (abandoned)
        spans, which chaos/drmc assert at every terminal state."""
        batch_span = TRACER.begin("prepare.batch", root=True,
                                  attributes={"n_claims": len(claims)})
        todo: List[_BatchClaim] = []
        try:
            return self._prepare_batch_spanned(claims, batch_span, todo)
        finally:
            for b in todo:
                span = b.span
                if span is not None:
                    # Idempotent: the normal path already closed it in
                    # the results loop — this catches crash/abort paths.
                    span.abandon("prepare aborted mid-batch")
            batch_span.end()

    def _prepare_batch_spanned(self, claims: List[Dict],
                               batch_span, todo: List[_BatchClaim]
                               ) -> Dict[str, PrepareResult]:
        results: Dict[str, PrepareResult] = {}
        batch_timings: Dict[str, float] = {}
        with self._lock:
            # Pure phase first (no side effects): idempotency check,
            # allocation parsing, opaque-config resolution and the FULL
            # device records up front (names, chip indices, configs,
            # deterministic CDI ids), so config errors return before any
            # state is recorded and the intent record below already
            # names every chip each member will touch — a SIGKILL
            # mid-apply must leave a record that rollback AND the
            # startup time-slice reconciliation's `held` set can see.
            with TRACER.span("prepare.decode",
                             parent=batch_span) as t_decode:
                for claim in claims:
                    uid = claim["metadata"]["uid"]
                    if uid in results or any(b.uid == uid for b in todo):
                        continue  # duplicate uid in one RPC: one result
                    existing = self._checkpoint.claims.get(uid)
                    if existing is not None and \
                            existing.state == PREPARE_COMPLETED and \
                            self._cdi.claim_spec_exists(uid):
                        # Idempotent fast path — but only while the claim
                        # CDI spec is actually on disk. A crash can persist
                        # the terminal checkpoint sync yet lose the spec's
                        # never-synced rename (drmc crash point: every
                        # clean-image crash past the fdatasync); vouching
                        # for the lost file would hand kubelet CDI ids that
                        # fail container creation. Fall through instead:
                        # the full pipeline re-applies side effects
                        # idempotently and rewrites the spec.
                        results[uid] = PrepareResult(devices=[
                            _prepared_device_from_record(r)
                            for r in existing.devices])
                        continue
                    try:
                        config_results = self._resolve_claim_configs(claim)
                        records = self._build_records(uid, config_results)
                    except Exception as e:  # noqa: BLE001 — claim error
                        results[uid] = PrepareResult(
                            error=f"prepare devices: {e}")
                        continue
                    configs = [cr.config for cr in config_results]
                    todo.append(_BatchClaim(
                        uid=uid, claim=claim,
                        config_results=config_results,
                        records=records,
                        # The member's prepare.claim span continues the
                        # trace the RPC layer stamped into the claim
                        # annotation (fresh root when none — direct
                        # DeviceState callers trace too). Closed in the
                        # results loop; the prepare_batch finally
                        # abandons it on crash paths.
                        span=TRACER.begin(
                            "prepare.claim", root=True,
                            traceparent=(claim["metadata"].get(
                                "annotations") or {}).get(
                                TRACEPARENT_ANNOTATION),
                            attributes={"claim_uid": uid}),
                        hazardous=any(self._config_hazard(c)
                                      for c in configs),
                        # Passthrough (IOMMU-group rebinds yank sibling
                        # chips) and unknown config kinds serialize on
                        # the hazard lock; everything else — including
                        # multiprocess, whose Deployment and daemon dirs
                        # are per-claim — is covered by its chip locks.
                        serialize=any(
                            not isinstance(c, (apitypes.TpuConfig,
                                               apitypes.SubsliceConfig))
                            for c in configs),
                        # Only sharing strategies block (tpuctl execs,
                        # coordinator-Deployment round trips); env-only
                        # applies are too cheap for pool dispatch to win.
                        slow_apply=any(
                            not isinstance(c, apitypes.SubsliceConfig)
                            and (not isinstance(c, apitypes.TpuConfig)
                                 or c.sharing is not None)
                            for c in configs)))
            batch_timings["decode"] = t_decode.duration_s
            if not todo:
                return results
            for b in todo:
                self._checkpoint.claims[b.uid] = PreparedClaim(
                    uid=b.uid, state=PREPARE_STARTED,
                    name=b.claim["metadata"].get("name", ""),
                    namespace=b.claim["metadata"].get("namespace", ""),
                    devices=b.records)
            intent_token: Optional[int] = None
            hazardous = [b for b in todo if b.hazardous]
            if hazardous:
                # ONE transient mid-prepare journal record covering
                # every hazardous member. Non-hazardous members skip the
                # durable intent entirely: their only side effect is the
                # claim CDI spec, which startup orphan GC and the
                # unconditional unprepare delete reconcile without a
                # record. The group sync happens OUTSIDE the state lock
                # (below) so concurrent RPCs coalesce their fdatasyncs.
                with TRACER.span("prepare.checkpoint_start",
                                 parent=batch_span) as t_intent:
                    try:
                        intent_token = self._ckpt_mgr.journal_commit(
                            self._checkpoint,
                            present=[b.uid for b in hazardous],
                            intent=True)
                    except Exception as e:  # noqa: BLE001 — no side
                        # effects applied for ANY member yet and disk
                        # never saw the records: unwind them in memory
                        # and fail the batch; kubelet retries each claim
                        # from scratch.
                        for b in todo:
                            self._checkpoint.claims.pop(b.uid, None)
                            results[b.uid] = PrepareResult(
                                error=f"intent store: {e}")
                        return results
                batch_timings["checkpoint_start"] = t_intent.duration_s
        if intent_token is not None:
            # Durable intent BEFORE any side effect runs — the same
            # store-before-side-effects contract as the slot scheme,
            # with the sync group-committed across RPCs.
            with TRACER.span("prepare.checkpoint_start",
                             parent=batch_span) as t_ibar:
                try:
                    self._ckpt_mgr.journal_barrier(intent_token)
                except Exception as e:  # noqa: BLE001 — sync failed
                    # before any side effect: abort the batch. The
                    # appended intent record may still be durable; a
                    # restart replays it as PrepareStarted and
                    # unprepare/GC finish the cleanup — the same
                    # recovery as a crash mid-prepare.
                    self._abort_unsynced_intent(todo, results, e)
                    return results
            batch_timings["checkpoint_start"] += t_ibar.duration_s

        # Side-effect application OUTSIDE the global lock: members on
        # disjoint chip sets run concurrently, chip locks serialize
        # overlaps (two subslice/time-slicing claims of one chip), the
        # hazard lock serializes configs whose effects span beyond the
        # claim's own chips. Checkpoint reads (exclusivity guards) stay
        # stable because every mutation waits for the terminal phase.
        # Claim-spec writes are SUBMITTED here (async pool) and awaited
        # at the commit barrier below, overlapping the terminal append
        # + group sync.
        with TRACER.span("prepare.apply", parent=batch_span) as t_apply:
            self._apply_batch(todo)
            # One writer task for the whole batch's claim specs: its
            # write+rename loop overlaps the terminal append + group
            # sync.
            self._submit_spec_writes(todo)
        batch_timings["apply"] = t_apply.duration_s

        token: Optional[int] = None
        failed: List[_BatchClaim] = []
        survivors: List[_BatchClaim] = []
        # uid -> rollback error for members whose unwind could not
        # complete (degraded to a deferred PrepareStarted record).
        deferred: Dict[str, str] = {}
        with self._lock:
            failed = [b for b in todo if b.error is not None]
            survivors = [b for b in todo if b.error is None]
            for b in failed:
                # Failed members never submitted a spec write (the
                # submission is the apply's last step), so the unwind's
                # spec delete cannot race a pending write.
                err = self._unwind_claim(b.uid)
                if err is not None:
                    deferred[b.uid] = err
            for b in survivors:
                self._checkpoint.claims[b.uid].state = PREPARE_COMPLETED
            with TRACER.span("prepare.checkpoint_final",
                             parent=batch_span) as t_final:
                try:
                    # The group commit: every member's terminal outcome
                    # — survivors completed, failures erased, deferred
                    # unwinds parked PrepareStarted — in ONE journal
                    # record; the durable sync is the barrier below,
                    # outside this lock.
                    token = self._ckpt_mgr.journal_commit(
                        self._checkpoint,
                        present=[b.uid for b in survivors]
                        + sorted(deferred),
                        absent=[b.uid for b in failed
                                if b.uid not in deferred])
                except Exception as e:  # noqa: BLE001 — terminal append
                    # failed: survivors are fully applied but not
                    # durably completed; a crash now would replay them
                    # as PrepareStarted. Unwind them too and persist the
                    # rollback, so the kubelet retry starts from a clean
                    # slate instead of half-committed state.
                    self._await_cdi(todo)
                    self._rollback_survivors_locked(
                        todo, survivors, deferred,
                        f"checkpoint store: {e}")
            batch_timings["checkpoint_final"] = t_final.duration_s

        if token is not None:
            with TRACER.span("prepare.checkpoint_final",
                             parent=batch_span) as t_fbar:
                try:
                    # The durable half of the group commit: one
                    # fdatasync shared by every RPC whose barrier
                    # overlaps.
                    self._ckpt_mgr.journal_barrier(token)
                except Exception as e:  # noqa: BLE001 — the record may
                    # or may not be durable; roll the survivors back and
                    # persist the erasure through the synced slot path.
                    self._rollback_after_sync_failure(
                        todo, survivors, deferred, e)
                    token = None
            batch_timings["checkpoint_final"] += t_fbar.duration_s
        if token is not None:
            # Commit barrier: claim-spec writes must have landed before
            # any success externalizes. A member whose spec write failed
            # is rolled back — its terminal record is superseded by a
            # synced full-image store.
            cdi_failed = self._await_cdi(todo)
            if cdi_failed:
                with self._lock:
                    self._rollback_survivors_locked(
                        todo, cdi_failed, deferred, "claim spec write")
                lost = {b.uid for b in cdi_failed}
                survivors = [b for b in survivors if b.uid not in lost]
                failed = failed + cdi_failed

        with self._lock:
            # `total` is the batch root span's live duration — the one
            # clock every other phase key is a slice of.
            batch_timings["total"] = batch_span.duration_s
            for b in todo:
                if b.uid in deferred:
                    log.warning(
                        "prepare rollback for %s incomplete (%s); claim "
                        "left PrepareStarted for deferred unwind", b.uid,
                        deferred[b.uid])
                    results[b.uid] = PrepareResult(
                        error=f"{b.error}; rollback deferred: "
                              f"{deferred[b.uid]}")
                elif b.error is not None:
                    results[b.uid] = PrepareResult(error=b.error)
                else:
                    if token is not None and b.span is not None:
                        # The batch shares ONE terminal journal append
                        # + group sync; attribute the member's share as
                        # a synthesized child so the claim's tree shows
                        # where its durability cost went.
                        TRACER.record_span(
                            "prepare.journal",
                            batch_timings.get("checkpoint_final", 0.0),
                            parent=b.span)
                    results[b.uid] = PrepareResult(devices=[
                        _prepared_device_from_record(r)
                        for r in b.records])
                if b.span is not None:
                    if b.error is not None:
                        b.span.abandon(b.error)
                    else:
                        b.span.end()

            if survivors and not failed:
                self.last_batch_breakdown = {
                    **{k: v * 1e3 for k, v in batch_timings.items()},
                    "n_claims": float(len(todo)),
                }
            if len(todo) == 1 and todo[0].error is None \
                    and not deferred:
                b = todo[0]
                timings = dict(b.timings)
                timings.setdefault("cdi_wait", 0.0)
                timings["decode"] = batch_timings["decode"]
                if "checkpoint_start" in batch_timings:
                    timings["checkpoint_start"] = \
                        batch_timings["checkpoint_start"]
                timings["checkpoint_final"] = \
                    batch_timings["checkpoint_final"]
                timings["total"] = batch_timings["total"]
                self.last_prepare_breakdown = {
                    k: v * 1e3 for k, v in timings.items()}
        return results

    def _abort_unsynced_intent(self, todo: List[_BatchClaim],
                               results: Dict[str, PrepareResult],
                               e: Exception) -> None:
        """Intent group sync failed before any side effect: erase the
        batch from memory and fail every member (kubelet retries from
        scratch). The appended record's durability is unknown; a
        restart that replays it sees plain crash-mid-prepare state."""
        with self._lock:
            for b in todo:
                self._checkpoint.claims.pop(b.uid, None)
                results[b.uid] = PrepareResult(
                    error=f"intent store: {e}")

    def _await_cdi(self, todo: List[_BatchClaim]) -> List[_BatchClaim]:
        """The CDI half of the commit barrier: wait out the batch's
        spec-write task; a member whose write failed is marked failed
        and returned for rollback. Must run before any unwind deletes
        spec files (a delete racing a pending write would lose)."""
        failed = []
        for b in todo:
            fut = b.cdi_future
            if fut is None:
                continue
            b.cdi_future = None
            with TRACER.span("prepare.cdi_wait",
                             parent=b.span) as t_wait:
                try:
                    # Shared future: the first member's wait covers the
                    # batch, the rest read the cached result.
                    errors = fut.result()
                except Exception as e:  # noqa: BLE001 — whole task died
                    errors = {b.uid: str(e)}
            b.timings["cdi_wait"] = (b.timings.get("cdi_wait", 0.0)
                                     + t_wait.duration_s)
            err = errors.get(b.uid)
            if err is not None:
                if b.error is None:
                    b.error = f"prepare devices: {err}"
                failed.append(b)
        return failed

    def _rollback_survivors_locked(self, todo: List[_BatchClaim],
                                   members: List[_BatchClaim],
                                   deferred: Dict[str, str],
                                   err_msg: str) -> None:
        """Terminal commit could not be made durable (append failure,
        sync failure, or a member's spec write failed after the sync):
        unwind `members` (side effects reversed, specs deleted,
        checkpoint entries erased) and persist the rollback through the
        synced slot path, which supersedes whatever the journal record
        announced. Caller holds _lock and has awaited the CDI futures
        of every member being unwound."""
        for b in members:
            if b.error is None:
                b.error = err_msg
            err = self._unwind_claim(b.uid)
            if err is not None:
                deferred[b.uid] = err
        try:
            self._ckpt_mgr.store(self._checkpoint)
        except Exception as e2:  # noqa: BLE001 — rollback store failed
            # as well: degrade every not-yet-deferred member to a
            # deferred PrepareStarted record so a later unprepare — or
            # the next driver start — can finish the unwind. Never
            # silently dropped.
            for b in todo:
                if b.uid in deferred:
                    continue
                if b.error is None:
                    b.error = f"checkpoint store: {e2}"
                self._checkpoint.claims[b.uid] = PreparedClaim(
                    uid=b.uid, state=PREPARE_STARTED,
                    name=b.claim["metadata"].get("name", ""),
                    namespace=b.claim["metadata"].get(
                        "namespace", ""),
                    devices=b.records)
                deferred[b.uid] = str(e2)
            try:
                self._ckpt_mgr.store(self._checkpoint)
            # Deliberate R7 waiver: every member was already degraded
            # to a deferred PrepareStarted record just above (the
            # compensation), and this is the RETRY of the rollback
            # store itself failing — nothing is left to unwind; the
            # durable intent record (if hazardous) still names the
            # members' chips for the next start's recovery.
            # dralint: ignore[R7] — the rollback store IS the unwind; retrying it has nothing left to compensate
            except Exception:  # noqa: BLE001
                log.warning("failed-batch record store failed",
                            exc_info=True)

    def _rollback_after_sync_failure(self, todo: List[_BatchClaim],
                                     survivors: List[_BatchClaim],
                                     deferred: Dict[str, str],
                                     e: Exception) -> None:
        """Terminal group sync failed: the journal record's durability
        is unknown. Await the spec writes (the unwind deletes specs),
        then unwind the survivors and persist the erasure through the
        synced slot path, which out-ranks the unsynced record."""
        self._await_cdi(todo)
        with self._lock:
            self._rollback_survivors_locked(
                todo, survivors, deferred, f"checkpoint store: {e}")

    def _apply_batch(self, todo: List[_BatchClaim]) -> None:
        """Run every member's side-effect application; failures land in
        the member's `error` (never raises). Pool dispatch pays off only
        when at least two members genuinely block (tpuctl execs,
        coordinator-Deployment round trips) AND can actually overlap
        (serialize-flagged members queue on the hazard lock anyway);
        otherwise the batch stays on the calling thread — measured: the
        pool costs ~0.07 ms/claim on env-only applies, a pure loss."""
        parallelizable = sum(1 for b in todo
                             if b.slow_apply and not b.serialize)
        if len(todo) == 1 or parallelizable < 2:
            for b in todo:
                self._apply_member(b)
            return
        if self._apply_pool is None:
            self._apply_pool = ThreadPoolExecutor(
                max_workers=min(8, max(2, len(self._chip_locks))),
                thread_name_prefix="tpu-dra-apply")
        futures = [self._apply_pool.submit(self._apply_member, b)
                   for b in todo]
        for f in futures:
            f.result()

    def _apply_member(self, b: _BatchClaim) -> None:
        """One member's side effects under its locks. Never raises —
        the terminal phase reads `b.error` for transactional rollback."""
        try:
            # Injection site: mid-batch apply failure — the loser must
            # roll back while its batch siblings commit durably.
            FAULTS.check("prepare.batch_apply", claim_uid=b.uid)
            with ExitStack() as stack:
                # Lock order is global (hazard first, then ascending
                # chip index), so overlapping members cannot deadlock.
                if b.serialize:
                    stack.enter_context(self._hazard_lock)
                for idx in sorted({r["chip_index"] for r in b.records}):
                    stack.enter_context(self._chip_locks[idx])
                self._apply_devices(b)
        except Exception as e:  # noqa: BLE001 — report as claim error
            b.error = f"prepare devices: {e}"

    def _unwind_claim(self, uid: str) -> Optional[str]:
        """Transactional unwind of one failed batch member (caller holds
        _lock): reverse the side effects the records name (exclusive
        mode, multiprocess daemons, time slices, VFIO rebinds), delete
        the claim CDI spec, and erase the checkpoint entry — so the
        kubelet's retry re-runs prepare from scratch (idempotent) and an
        abandoned claim is *cleanly unallocated*, not half-held. The
        batch's single terminal store persists the erasure; no store
        happens here.

        If the unwind itself fails (a chip wedged mid-rebind), keep the
        PrepareStarted record so a later unprepare — or the next driver
        start — can finish the rollback, and return the error. Never
        raises."""
        prepared = self._checkpoint.claims.get(uid)
        try:
            if prepared is not None:
                self._unprepare_devices(uid, prepared)
            self._cdi.delete_claim_spec_file(uid)
            self._checkpoint.claims.pop(uid, None)
            return None
        except Exception as rollback_err:  # noqa: BLE001 — degrade to
            # deferred rollback (unprepare/startup GC both handle
            # PrepareStarted records); re-insert in case deletion
            # happened before the failure.
            if prepared is not None:
                prepared.state = PREPARE_STARTED
                self._checkpoint.claims[uid] = prepared
            return str(rollback_err)

    def _resolve_claim_configs(self, claim: Dict) -> List["_ConfigResult"]:
        """The pure phase of prepare: parse allocation results and resolve
        opaque configs. Raises PrepareError; applies no side effects."""
        allocation = ((claim.get("status") or {}).get("allocation") or {})
        results = [r for r in (allocation.get("devices") or {}).get("results", [])
                   if r.get("driver") == self._driver_name]
        if not results:
            raise PrepareError("claim has no allocation results for this driver")
        return self._resolve_configs(allocation, results)

    def _config_hazard(self, cfg: object) -> bool:
        """Will applying `cfg` mutate state beyond the claim CDI spec file?
        Hazardous configs (chip-mode changes, VFIO rebinds, coordinator
        Deployments) need a durable PrepareStarted record before they run
        so a crash mid-prepare can be rolled back. The predicate names
        only the KNOWN-SAFE cases and answers True for everything else:
        if a new side-effectful branch lands in _apply_sharing_config
        without a matching entry here, the drift costs one extra intent
        store — it can never lose a rollback record."""
        if isinstance(cfg, apitypes.SubsliceConfig):
            return False  # env-only: core ranges + HBM limit
        if isinstance(cfg, apitypes.TpuConfig):
            sharing = cfg.sharing
            if sharing is None:
                return False
            if sharing.is_time_slicing():
                # Non-hazardous even when it WILL set a time slice: the
                # setting is chip-level and reconciled at startup (every
                # chip not held by a checkpointed time-slicing claim is
                # reset to default in __init__), so a crash between
                # set_timeslice and the terminal store self-heals without
                # a durable intent record.
                return False
            return True  # multiprocess / future strategies: fail safe
        return True  # Passthrough and any unknown config kind

    def _build_records(self, uid: str,
                       config_results: List["_ConfigResult"]) -> List[Dict]:
        """The PURE half of prepare: checkpoint device records with
        deterministic CDI ids for every allocation result. Runs before
        the intent store so a mid-apply crash leaves a record naming
        every chip the claim touches (rollback + the startup
        reconciliation's `held` set both depend on that)."""
        records: List[Dict] = []
        for cr in config_results:
            is_passthrough = isinstance(cr.config, apitypes.PassthroughConfig)
            for result in cr.results:
                dev = self.allocatable.get(result["device"])
                if dev is None:
                    raise PrepareError(
                        f"allocated device {result['device']!r} is not on "
                        "this node")
                # Passthrough claims get ONLY the claim device: the VFIO
                # rebind removes /dev/accelN from the host, so the standard
                # per-chip spec's deviceNodes would point at a dead path
                # and fail container creation.
                cdi_ids = ([self._cdi.get_claim_device(uid)]
                           if is_passthrough else
                           [self._cdi.get_standard_device(dev.chip.uuid),
                            self._cdi.get_claim_device(uid)])
                records.append({
                    "type": dev.type,
                    "device": dev.name,
                    "request": result.get("request", ""),
                    "chip_index": dev.chip.index,
                    "chip_uuid": dev.chip.uuid,
                    "pool": self._node_name,
                    "config": cr.config.to_dict(),
                    "cdi_ids": cdi_ids,
                })
        return records

    def _apply_devices(self, b: _BatchClaim) -> None:
        """The side-effect half of prepare: sharing setup, passthrough
        rebinds, exclusivity guards, and the claim CDI spec write —
        SUBMITTED async as the final step (b.cdi_future; the commit
        barrier awaits it), so the tmp-write + rename overlap the
        terminal checkpoint work. The caller persisted the records for
        all of this before any of it runs (crash/failure rollback)."""
        claim, config_results, timings = b.claim, b.config_results, b.timings
        uid = claim["metadata"]["uid"]

        chip_indices: set = set()
        claim_chips: Dict[int, Chip] = {}
        subslice_cores: Dict[int, set] = {}
        subslice_hbm_total = 0
        claim_env: Dict[str, str] = {}
        claim_mounts: List[Dict] = []
        claim_device_nodes: List[Dict] = []

        for cr in config_results:
            group_chips = self._chips_for_results(cr.results)
            with TRACER.span("prepare.sharing", parent=b.span) as t_sh:
                sharing_env = self._apply_sharing_config(uid, cr,
                                                         group_chips)
            timings["sharing"] = (timings.get("sharing", 0.0)
                                  + t_sh.duration_s)
            claim_env.update(sharing_env.get("env", {}))
            claim_mounts.extend(sharing_env.get("mounts", []))
            with TRACER.span("prepare.guards", parent=b.span) as t_gd:
                for result in cr.results:
                    dev = self.allocatable[result["device"]]
                    chip_indices.add(dev.chip.index)
                    claim_chips[dev.chip.index] = dev.chip
                    if dev.type == deviceinfo.DEVICE_TYPE_SUBSLICE:
                        ss = dev.subslice
                        subslice_cores.setdefault(
                            dev.chip.index, set()).update(
                            range(ss.core_start,
                                  ss.core_start + ss.core_count))
                        subslice_hbm_total += ss.hbm_bytes
                    if isinstance(cr.config, apitypes.PassthroughConfig):
                        if self._pt_manager is not None:
                            self._assert_group_exclusive(
                                dev.chip, uid, passthrough=True)
                        self._backend.set_exclusive_mode(dev.chip.index,
                                                         True)
                        claim_env["TPU_PASSTHROUGH"] = "true"
                        if self._pt_manager is not None:
                            # Full VFIO rebind: the chip leaves the
                            # accel driver; the claim gets
                            # /dev/vfio/<group> nodes instead of a
                            # usable /dev/accelN. Rebinding yanks every
                            # function in the IOMMU group, which the
                            # exclusivity assert above made safe.
                            group = self._pt_manager.configure(
                                dev.chip,
                                sibling_dev_paths=self._group_dev_paths(
                                    dev.chip))
                            claim_device_nodes.extend(
                                n for n in
                                self._pt_manager.cdi_device_nodes(group)
                                if n not in claim_device_nodes)
                    elif self._pt_manager is not None:
                        # Reverse guard: a normal claim must not land on
                        # a chip whose IOMMU group a passthrough claim
                        # holds — its /dev/accelN is gone while the
                        # group sits on vfio-pci.
                        self._assert_group_exclusive(
                            dev.chip, uid, passthrough=False)
            timings["guards"] = (timings.get("guards", 0.0)
                                 + t_gd.duration_s)

        if subslice_cores:
            # Aggregate across all subslices of the claim. Single-chip claims
            # get the scalar var; multi-chip subslice claims get per-chip vars.
            if len(subslice_cores) == 1:
                (cores,) = subslice_cores.values()
                claim_env["TPU_SUBSLICE_CORES"] = _core_ranges(cores)
            else:
                for idx, cores in sorted(subslice_cores.items()):
                    claim_env[f"TPU_SUBSLICE_CORES_{idx}"] = _core_ranges(cores)
            claim_env["TPU_HBM_LIMIT_BYTES"] = str(subslice_hbm_total)

        claim_env.update(visible_chips_env(sorted(chip_indices)))
        # Allocation -> mesh handoff (SURVEY §17): export the allocated
        # chips' torus coordinates + declared slice topology next to
        # TPU_VISIBLE_CHIPS, so the workload's mesh builder
        # (workloads.meshbuild) lays ranks over the SAME allocation the
        # scheduler scored. Empty when the inventory publishes no
        # topology (coordinate-less nodes keep their exact old env).
        claim_env.update(export_topology_env(
            [claim_chips[i] for i in sorted(claim_chips)]))
        # Trace-context export (SURVEY §19): the claim's span rides the
        # CDI env next to TPU_CHIP_COORDS, so the workload-side mesh
        # build and the CD daemon readiness mirror continue the SAME
        # trace the scheduler started at allocation.
        if b.span is not None:
            tp = b.span.traceparent()
            if tp:
                claim_env[ENV_TRACEPARENT] = tp
        # CPU half on THIS thread (json + the cdi.claim_write fault
        # site, so a config/ENOSPC-simulating failure takes the plain
        # apply-error rollback); only the pure-I/O half (tmp write +
        # rename, GIL-released syscalls) goes to the writer pool. The
        # async path is bypassed while a drmc vfs recorder is installed:
        # the crash enumerator needs one deterministic durable-op
        # sequence, and the sync write exercises the same crash images
        # (the spec rename is never dir-synced either way).
        with TRACER.span("prepare.cdi_write", parent=b.span) as t_cdi:
            path, text = self._cdi.serialize_claim_spec(
                uid, claim_env, mounts=claim_mounts or None,
                device_nodes=claim_device_nodes or None)
            if self._cdi_pool is not None and vfs.installed() is None:
                # Deferred to the batch's single writer task (submitted
                # at the end of the apply phase): the write+rename
                # syscalls (GIL-released) overlap the terminal append +
                # group sync, and the commit barrier (_await_cdi)
                # collects them before any result externalizes.
                b.cdi_spec = (path, text)
            else:
                self._cdi.write_claim_spec(path, text)
        timings["cdi_write"] = t_cdi.duration_s

    def _submit_spec_writes(self, todo: List[_BatchClaim]) -> None:
        """ONE writer task for every member's pending spec: a single
        pool wakeup + a sequential loop of GIL-releasing syscalls.
        Sub-ms per-member tasks measured ~7x slower than this (executor
        wakeup thrash). Members that failed apply never write a spec."""
        pending = [(b.uid, b.cdi_spec, b.timings, b.span) for b in todo
                   if b.cdi_spec is not None and b.error is None]
        for b in todo:
            b.cdi_spec = None
        if not pending:
            return
        fut = self._cdi_pool.submit(self._write_claim_specs, pending)
        for b in todo:
            if b.error is None:
                b.cdi_future = fut

    def _write_claim_specs(self, pending) -> Dict[str, str]:
        """The batch's spec I/O on the writer pool: uid -> error for
        any member whose write failed (isolation); the timings dicts
        are member-private, ordered against readers by the future."""
        errors: Dict[str, str] = {}
        for uid, (path, text), timings, span in pending:
            # The span is parented explicitly (this runs on the writer
            # pool thread — the thread-local stack is the RPC thread's).
            with TRACER.span("prepare.cdi_io", parent=span) as t_io:
                try:
                    self._cdi.write_claim_spec(path, text)
                except Exception as e:  # noqa: BLE001 — isolate the
                    errors[uid] = str(e)  # member
            timings["cdi_io"] = (timings.get("cdi_io", 0.0)
                                 + t_io.duration_s)
        return errors

    def _group_chip_indices(self, chip: Chip) -> List[int]:
        """Indices of every chip sharing `chip`'s IOMMU group (including
        itself); just [chip.index] when topology is unknown."""
        group = self._pt_manager.group_of(chip)
        if group is None:
            return [chip.index]
        addrs = set(self._pt_manager.group_devices(group))
        return [c.index for c in self._backend.chips()
                if c.pci_address in addrs] or [chip.index]

    def _group_dev_paths(self, chip: Chip) -> Dict[str, str]:
        group = self._pt_manager.group_of(chip)
        if group is None:
            return {}
        addrs = set(self._pt_manager.group_devices(group))
        return {c.pci_address: c.dev_path for c in self._backend.chips()
                if c.pci_address in addrs and c.index != chip.index}

    def _assert_group_exclusive(self, chip: Chip, claim_uid: str,
                                *, passthrough: bool) -> None:
        """VFIO IOMMU-group exclusivity: a passthrough claim owns its whole
        group, so (a) a passthrough prepare conflicts with ANY other claim
        holding a group chip, and (b) a normal prepare conflicts with a
        PASSTHROUGH claim holding a group chip (the rebind destroyed its
        /dev/accelN). Runs during a batch's apply phase. The pipelined
        server overlaps RPCs on disjoint claims, so checkpoint mutation
        is no longer globally quiescent here — safety holds because
        every member's PrepareStarted record lands (under self._lock)
        BEFORE any apply begins: of two racing conflicting claims, at
        least one's guard observes the other's record and refuses (both
        may refuse — kubelet retries break the tie; they can never both
        succeed). The iteration snapshot below keeps a concurrent
        terminal-phase mutation from crashing the guard mid-iteration.
        (Sibling handling analog: device_state.go:526-552.)"""
        group_indices = set(self._group_chip_indices(chip))
        for uid, prepared in list(self._checkpoint.claims.items()):
            if uid == claim_uid:
                continue
            for record in prepared.devices:
                if record.get("chip_index") not in group_indices:
                    continue
                other_is_pt = (record.get("config") or {}).get(
                    "kind") == apitypes.PASSTHROUGH_CONFIG_KIND
                if passthrough or other_is_pt:
                    raise PrepareError(
                        f"chip {chip.index} shares IOMMU group with chip "
                        f"{record['chip_index']} held by claim {uid}; "
                        "VFIO passthrough requires the whole group")

    def _chips_for_results(self, results: List[Dict]) -> List[Chip]:
        chips: Dict[int, Chip] = {}
        for result in results:
            dev = self.allocatable.get(result["device"])
            if dev is None:
                raise PrepareError(
                    f"allocated device {result['device']!r} is not on this node")
            chips[dev.chip.index] = dev.chip
        return [chips[i] for i in sorted(chips)]

    # -- opaque config resolution -------------------------------------------

    def _resolve_configs(self, allocation: Dict,
                         results: List[Dict]) -> List[_ConfigResult]:
        """GetOpaqueDeviceConfigs + config->results mapping
        (device_state.go:337-380, 646-699)."""
        configs = self._decode_opaque_configs(allocation)
        out: List[_ConfigResult] = []
        for result in results:
            dev = self.allocatable.get(result["device"])
            dev_type = dev.type if dev else deviceinfo.DEVICE_TYPE_CHIP
            chosen: Optional[Tuple[int, object, str]] = None
            for rank, (source, requests, cfg) in enumerate(configs):
                if requests and result.get("request") not in requests:
                    continue
                # Config kind must match the device type (device_state.go
                # :352-378): a request-targeted mismatch is an error, a
                # catch-all config of the wrong kind is skipped.
                if not _config_compatible(cfg, dev_type):
                    if requests:
                        raise PrepareError(
                            f"config kind {type(cfg).KIND} does not apply to "
                            f"{dev_type} device {result['device']!r}")
                    continue
                # Later entries win; FromClaim outranks FromClass because
                # claim configs are appended after class configs.
                chosen = (rank, cfg, source)
            if chosen is None:
                cfg = self._default_config(result)
                source = "default"
            else:
                _, cfg, source = chosen
            cfg.normalize()
            cfg.validate()
            for cr in out:
                if cr.config.to_dict() == cfg.to_dict() and cr.source == source:
                    cr.results.append(result)
                    break
            else:
                out.append(_ConfigResult(config=cfg, source=source,
                                         results=[result]))
        return out

    def _decode_opaque_configs(self, allocation: Dict):
        """Returns [(source, requests, config)] ordered FromClass-first so
        list order encodes precedence (GetOpaqueDeviceConfigs :646-699)."""
        entries = (allocation.get("devices") or {}).get("config", []) or []
        ordered = ([e for e in entries if e.get("source") == "FromClass"]
                   + [e for e in entries if e.get("source") != "FromClass"])
        decoded = []
        for entry in ordered:
            opaque = entry.get("opaque") or {}
            if opaque.get("driver") != self._driver_name:
                continue
            try:
                cfg = apischeme.StrictDecoder.decode(opaque.get("parameters", {}))
            except apischeme.DecodeError as e:
                raise PrepareError(f"invalid opaque config: {e}") from e
            decoded.append((entry.get("source", ""),
                            list(entry.get("requests") or []), cfg))
        return decoded

    def _default_config(self, result: Dict):
        dev = self.allocatable.get(result["device"])
        if dev is not None and dev.type == deviceinfo.DEVICE_TYPE_SUBSLICE:
            return apitypes.SubsliceConfig()
        return apitypes.TpuConfig.default()

    # -- sharing -------------------------------------------------------------

    def _apply_sharing_config(self, claim_uid: str, cr: _ConfigResult,
                              chips: List[Chip]) -> Dict:
        """applySharingConfig analog (device_state.go:567-615): returns CDI
        edit contributions {env, mounts}."""
        sharing = getattr(cr.config, "sharing", None)
        if sharing is None:
            return {}
        if sharing.is_time_slicing():
            if not featuregates.enabled(featuregates.TimeSlicingSettings):
                return {}
            if self._ts_manager is None:
                raise PrepareError("time-slicing requested but manager disabled")
            self._ts_manager.set_timeslice(
                chips, sharing.time_slicing_config
                or apitypes.TimeSlicingConfig())
            return {"env": {"TPU_SHARING_STRATEGY": "time-slicing"}}
        if sharing.is_multiprocess():
            if self._mp_manager is None:
                raise PrepareError("multiprocess requested but manager disabled")
            daemon = self._mp_manager.start(
                claim_uid, chips,
                sharing.multiprocess_config or apitypes.MultiprocessConfig())
            edits = daemon.cdi_edits()
            edits.setdefault("env", {})["TPU_SHARING_STRATEGY"] = "multiprocess"
            return edits
        return {}

    # ------------------------------------------------------------------
    # Unprepare
    # ------------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> Optional[str]:
        """Returns error string or None (idempotent: unknown claim is a
        no-op success, device_state.go:218-273). A batch of one."""
        return self.unprepare_batch([claim_uid])[claim_uid]

    def unprepare_batch(self, claim_uids: List[str]
                        ) -> Dict[str, Optional[str]]:
        """Unprepare every claim of one NodeUnprepareResources RPC with
        a single group-committed terminal store (N claims, 1 fdatasync).
        Per-claim semantics are the single-claim contract: unknown claims
        are no-op successes (orphan CDI specs still scrubbed), a failed
        device unwind isolates to its claim, and a failed store reinserts
        every removed entry — memory must not run ahead of disk (chaos
        seed 5), or the retry would no-op while the on-disk entries
        survive to resurrect at the next restart."""
        results: Dict[str, Optional[str]] = {}
        token: Optional[int] = None
        removed: List[Tuple[str, PreparedClaim]] = []
        to_unwind: List[Tuple[str, PreparedClaim]] = []
        seen: set = set()
        with self._lock:
            for claim_uid in claim_uids:
                if claim_uid in seen:
                    continue  # duplicate uid in one RPC
                seen.add(claim_uid)
                prepared = self._checkpoint.claims.get(claim_uid)
                if prepared is None:
                    # Unknown claim: still scrub any orphan CDI spec — a
                    # crash after a non-hazardous prepare's CDI write but
                    # before its terminal checkpoint store can leave one.
                    self._cdi.delete_claim_spec_file(claim_uid)
                    results[claim_uid] = None
                    continue
                to_unwind.append((claim_uid, prepared))
        # Device unwind OUTSIDE the global lock: _unprepare_devices
        # serializes on the hazard/chip locks, and a concurrent batch's
        # apply phase can hold those for a slow sharing round trip
        # (coordinator Deployment, seconds) — waiting for them under
        # _lock would convoy every pipelined RPC's pure phase (and its
        # SharedFlock hold) behind one slow apply. The checkpoint entry
        # stays in place until the terminal phase below, so exclusivity
        # guards keep refusing conflicting prepares mid-unwind, and
        # same-uid RPCs are already ordered by the pipeline.
        unwound: List[Tuple[str, PreparedClaim]] = []
        for claim_uid, prepared in to_unwind:
            try:
                self._unprepare_devices(claim_uid, prepared)
            except Exception as e:  # noqa: BLE001 — isolate the claim
                results[claim_uid] = f"unprepare devices: {e}"
                continue
            unwound.append((claim_uid, prepared))
        with self._lock:
            for claim_uid, prepared in unwound:
                self._cdi.delete_claim_spec_file(claim_uid)
                if self._checkpoint.claims.pop(claim_uid, None) is not None:
                    removed.append((claim_uid, prepared))
                results[claim_uid] = None
            if removed:
                try:
                    token = self._ckpt_mgr.journal_commit(
                        self._checkpoint,
                        absent=[uid for uid, _ in removed])
                except Exception as e:  # noqa: BLE001 — reinsert ALL
                    # removed entries; their device unwinds are
                    # idempotent, so the retry re-runs them safely.
                    for claim_uid, prepared in removed:
                        self._checkpoint.claims[claim_uid] = prepared
                        results[claim_uid] = \
                            f"unprepare checkpoint store: {e}"
        if token is not None:
            try:
                # The durable half, outside the lock: concurrent RPCs
                # coalesce on one fdatasync (group commit).
                self._ckpt_mgr.journal_barrier(token)
            except Exception as e:  # noqa: BLE001 — the removal record
                # may or may not be durable; reinsert and persist.
                self._reinsert_unprepared(removed, results, e)
        return results

    def _reinsert_unprepared(self, removed: List[Tuple[str, PreparedClaim]],
                             results: Dict[str, Optional[str]],
                             e: Exception) -> None:
        """Unprepare group sync failed: reinsert every removed entry
        (memory must not run ahead of disk) and persist the reinsertion
        through the synced slot path, which supersedes the unsynced
        removal record. If even that store fails, memory keeps the
        entries and the kubelet retry re-runs the idempotent unwind —
        whichever image a later crash leaves, the retry converges."""
        with self._lock:
            for claim_uid, prepared in removed:
                self._checkpoint.claims[claim_uid] = prepared
                results[claim_uid] = f"unprepare checkpoint store: {e}"
            try:
                self._ckpt_mgr.store(self._checkpoint)
            # The reinsertion above IS the compensation; the slot store
            # is best-effort durability for it (see docstring).
            # dralint: ignore[R7] — reinsertion above is the compensation; this store is best-effort durability for it
            except Exception:  # noqa: BLE001
                log.warning("unprepare rollback store failed",
                            exc_info=True)

    def _unprepare_devices(self, claim_uid: str, prepared: PreparedClaim) -> None:
        """Reverse a claim's chip-level side effects UNDER the same
        hazard/chip locks the apply phase takes (same global order:
        hazard first, then ascending chip index). The pipelined server
        overlaps RPCs on disjoint CLAIMS, but two claims can touch the
        same CHIP (time-slice siblings, a chip re-allocated while its
        old claim's unprepare is in flight) — without these locks an
        unprepare's reset could interleave with a concurrent prepare's
        configure on the same chip, which the pre-pipeline exclusive
        flock used to prevent."""
        chips: Dict[int, Chip] = {}
        strategies = set()
        passthrough_chips = []
        for record in prepared.devices:
            try:
                chip = self._backend.get_chip(record["chip_index"])
            except KeyError:
                continue  # chip vanished; nothing to reset
            chips[chip.index] = chip
            cfg = record.get("config") or {}
            sharing = cfg.get("sharing") or {}
            if sharing.get("strategy"):
                strategies.add(sharing["strategy"])
            if cfg.get("kind") == apitypes.PASSTHROUGH_CONFIG_KIND:
                passthrough_chips.append(chip)
        chip_list = [chips[i] for i in sorted(chips)]
        with ExitStack() as stack:
            if passthrough_chips:
                # IOMMU-group rebinds span beyond the claim's own chips
                # — serialize on the hazard lock like the apply phase.
                stack.enter_context(self._hazard_lock)
            for idx in sorted(chips):
                stack.enter_context(self._chip_locks[idx])
            if apitypes.MultiprocessStrategy in strategies \
                    and self._mp_manager:
                self._mp_manager.stop(claim_uid, chip_list)
            if apitypes.TimeSlicingStrategy in strategies \
                    and self._ts_manager:
                self._ts_manager.reset(chip_list)
            for chip in passthrough_chips:
                if self._pt_manager is not None:
                    # Return the chip to the accel driver before
                    # clearing the exclusive marker; unconfigure is
                    # idempotent, so a crashed half-prepared claim
                    # unwinds cleanly too.
                    self._pt_manager.unconfigure(chip)
                self._backend.set_exclusive_mode(chip.index, False)

    # ------------------------------------------------------------------
    # Health / inventory
    # ------------------------------------------------------------------

    def mark_unhealthy(self, chip_index: int) -> List[str]:
        """Mark all devices backed by the chip unhealthy; returns affected
        device names (UpdateDeviceHealthStatus analog,
        device_state.go:701-715). Takes _lock: the health-monitor thread
        mutates the set while republish reads it — unguarded, a republish
        mid-event could observe a torn inventory.

        Quarantine ladder: each TRANSITION into unhealthy (the chip was
        healthy a moment ago — a flap) is counted against the sliding
        window; crossing the threshold graduates the chip to quarantined
        and persists the ledger through the journal (group sync outside
        the lock). A persistence failure (health.flap site) leaves the
        chip transient-unhealthy — still excluded from publish — and the
        NEXT flap retries the graduation; the callback never dies."""
        token: Optional[int] = None
        with self._lock:
            affected = []
            uuid = None
            for name, dev in self.allocatable.items():
                if dev.chip.index == chip_index:
                    uuid = dev.chip.uuid
                    affected.append(name)
            if uuid is None:
                return affected
            is_flap = uuid not in self._unhealthy_uuids
            self._unhealthy_uuids.add(uuid)
            if is_flap and uuid not in self._checkpoint.quarantine:
                now = time.monotonic()
                hist = self._flap_history.setdefault(uuid, deque())
                hist.append(now)
                while hist and hist[0] < now - self._q_window_s:
                    hist.popleft()
                if len(hist) >= self._q_threshold:
                    token = self._quarantine_locked(
                        uuid, chip_index,
                        reason=f"{len(hist)} flaps within "
                               f"{self._q_window_s:g}s")
        if token is not None:
            self._quarantine_barrier(token)
        return affected

    def _quarantine_locked(self, uuid: str, chip_index: int, *,
                           reason: str) -> Optional[int]:
        """Graduate one chip to quarantined under _lock; returns the
        journal token to barrier outside the lock (None: persistence
        refused — the chip stays transient-unhealthy and the next flap
        retries). Never raises."""
        record = {
            "chip_index": chip_index,
            "reason": reason,
            "flaps": len(self._flap_history.get(uuid, ())),
            "since": time.time(),
        }
        if self._q_ttl_s > 0:
            record["ttl_s"] = self._q_ttl_s
        try:
            # Injection site: the graduation's journal append fails
            # (ENOSPC) — quarantine must degrade to transient-unhealthy,
            # not crash the health pipeline or half-persist.
            FAULTS.check("health.flap", chip_index=chip_index)
            self._checkpoint.quarantine[uuid] = record
            token = self._ckpt_mgr.journal_commit(
                self._checkpoint, quarantine=True)
        except Exception as e:  # noqa: BLE001 — degrade, retry on flap
            self._checkpoint.quarantine.pop(uuid, None)
            log.warning("quarantine of chip %d could not persist (%s); "
                        "chip stays transient-unhealthy until the next "
                        "flap retries", chip_index, e)
            return None
        self._flap_history.pop(uuid, None)
        quarantined_chips_gauge.set(len(self._checkpoint.quarantine))
        log.warning("chip %d QUARANTINED (%s); excluded from publish "
                    "until operator clear%s", chip_index, reason,
                    f" or TTL {self._q_ttl_s:g}s" if self._q_ttl_s > 0
                    else "")
        return token

    def _quarantine_barrier(self, token: int) -> None:
        """The durable half of a quarantine transition, outside _lock.
        A barrier failure keeps the in-memory transition (exclusion is
        the safe direction; a crash merely re-runs the ladder) and the
        next group sync or compaction re-covers the record."""
        try:
            # urgent: quarantine transitions are rare control-path
            # events — holding the adaptive group-commit window would
            # add latency with no co-committers to coalesce.
            self._ckpt_mgr.journal_barrier(token, urgent=True)
        except Exception:  # noqa: BLE001 — safe-direction degradation
            log.warning("quarantine journal sync failed; record may not "
                        "be durable until the next group sync",
                        exc_info=True)

    def mark_healthy(self, chip_index: int) -> List[str]:
        """Reverse of mark_unhealthy: a recovery event re-admits the chip's
        devices to the inventory. The reference cannot do this — a yanked
        GPU stays gone until driver restart (driver.go:263-264); the accel
        health stream's explicit 'recovered' records make re-add safe.

        A QUARANTINED chip is NOT re-admitted: recovery records are
        exactly what a flapping chip produces between its faults, and
        re-admitting on them is the ping-pong the ladder exists to stop.
        Only clear_quarantine (operator) or TTL expiry re-admits."""
        # Collect first, discard after: the chip's devices (chip +
        # subslices) share one uuid, and discarding inside the loop would
        # report only the first match.
        with self._lock:
            affected = [name for name, dev in self.allocatable.items()
                        if dev.chip.index == chip_index
                        and dev.chip.uuid in self._unhealthy_uuids
                        and dev.chip.uuid not in self._checkpoint.quarantine]
            for name in affected:
                self._unhealthy_uuids.discard(
                    self.allocatable[name].chip.uuid)
            return affected

    def quarantined_chips(self) -> Dict[str, Dict]:
        """uuid -> quarantine record snapshot (operator introspection)."""
        with self._lock:
            return {uuid: dict(rec) for uuid, rec in
                    self._checkpoint.quarantine.items()}

    def clear_quarantine(self, chip_index: Optional[int] = None
                         ) -> List[str]:
        """Operator seam: lift the quarantine of `chip_index` (None =
        every chip), persist the cleared ledger, and return the
        re-admitted device names so the caller republishes. The chip
        re-enters the inventory with a fresh flap window."""
        token: Optional[int] = None
        with self._lock:
            cleared = [uuid for uuid, rec in
                       self._checkpoint.quarantine.items()
                       if chip_index is None
                       or rec.get("chip_index") == chip_index]
            if not cleared:
                return []
            saved = {uuid: self._checkpoint.quarantine[uuid]
                     for uuid in cleared}
            affected = self._clear_quarantine_locked(cleared)
            try:
                token = self._ckpt_mgr.journal_commit(
                    self._checkpoint, quarantine=True)
            except Exception:  # noqa: BLE001 — degrade to the slot
                # scheme before giving up: a journal-only failure
                # (ENOSPC on the journal file) leaves the synced slot
                # store working, and its fresh seq supersedes the
                # still-durable graduation records (the same
                # maybe-durable supersede the prepare rollback paths
                # use). Leaving the clear memory-only instead would
                # resurrect the quarantine on restart — an operator
                # command silently undone (chaos-found, seed 7).
                log.warning("quarantine clear journal append failed; "
                            "degrading to slot store", exc_info=True)
                try:
                    self._ckpt_mgr.store(self._checkpoint)
                except Exception:  # noqa: BLE001 — nothing durable
                    # accepted the clear: ROLL IT BACK so memory and
                    # disk agree (the chip stays quarantined, loudly;
                    # the operator retries once storage recovers).
                    self._checkpoint.quarantine.update(saved)
                    quarantined_chips_gauge.set(
                        len(self._checkpoint.quarantine))
                    log.error("quarantine clear could not persist on "
                              "any scheme; clear rolled back for %s",
                              sorted(saved), exc_info=True)
                    return []
        if token is not None:
            self._quarantine_barrier(token)
        return affected

    def _clear_quarantine_locked(self, uuids: List[str]) -> List[str]:
        """Drop quarantine records + give the chips a fresh start
        (unhealthy mark and flap window cleared). Returns re-admitted
        device names. Caller holds _lock and persists."""
        affected = []
        for uuid in uuids:
            self._checkpoint.quarantine.pop(uuid, None)
            self._unhealthy_uuids.discard(uuid)
            self._flap_history.pop(uuid, None)
            affected.extend(name for name, dev in self.allocatable.items()
                            if dev.chip.uuid == uuid)
        quarantined_chips_gauge.set(len(self._checkpoint.quarantine))
        return sorted(affected)

    def healthy_devices(self) -> List[Dict]:
        """resourceapi device list excluding unhealthy AND quarantined
        chips (the republish path drops yanked devices,
        driver.go:283-293). Takes _lock so a health event landing
        mid-republish cannot yield a half-updated device set. Expired
        quarantine TTLs are lifted here — publish time is when the
        re-admission becomes visible anyway."""
        token: Optional[int] = None
        with self._lock:
            now = time.time()
            expired = [uuid for uuid, rec in
                       self._checkpoint.quarantine.items()
                       if rec.get("ttl_s")
                       and now >= rec.get("since", now) + rec["ttl_s"]]
            if expired:
                readmitted = self._clear_quarantine_locked(expired)
                log.info("quarantine TTL expired; re-admitting %s",
                         readmitted)
                try:
                    token = self._ckpt_mgr.journal_commit(
                        self._checkpoint, quarantine=True)
                except Exception:  # noqa: BLE001 — next transition
                    # re-persists; exclusion already lifted in memory.
                    log.warning("quarantine TTL clear could not persist",
                                exc_info=True)
            devices = [dev.to_resource_api()
                       for name, dev in sorted(self.allocatable.items())
                       if dev.chip.uuid not in self._unhealthy_uuids
                       and dev.chip.uuid not in self._checkpoint.quarantine]
        if token is not None:
            self._quarantine_barrier(token)
        return devices

    def prepared_claim_uids(self) -> List[str]:
        with self._lock:
            return list(self._checkpoint.claims)

    def checkpoint_snapshot(self) -> Checkpoint:
        with self._lock:
            return self._checkpoint
