"""TPU kubelet plugin (reference: cmd/gpu-kubelet-plugin, 4,869 LoC Go).

Publishes this node's TPU chips (and TensorCore subslices) as ResourceSlice
devices, and prepares/unprepares allocated ResourceClaims: CDI spec
injection, sharing config (time-slicing / multiprocess), checkpointing,
health monitoring.
"""
