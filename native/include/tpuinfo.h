/* libtpuinfo — native TPU chip discovery, topology, settings and health.
 *
 * TPU-native replacement for the reference driver's NVML surface
 * (k8s-dra-driver-gpu: cmd/gpu-kubelet-plugin/nvlib.go:59-61,134-183 device
 * enumeration; device_health.go:79-117 health events; compute-domain
 * nvlib.go:196-234 fabric/clique info). Where NVML speaks to the GPU driver
 * via cgo + ioctls, libtpuinfo reads the accel driver's ABI:
 *   <root>/dev/accel<N>                          chip char devices
 *   <root>/sys/class/accel/accel<N>/device/...   per-chip attributes
 *   <root>/sys/class/accel/health_events         appended event records
 *
 * The filesystem root is injectable (tpuinfo_init) so the complete library —
 * not a mock of it — runs against a synthetic tree in tests and in
 * clusters without TPUs (SURVEY.md §7.3: the fake-able hardware seam).
 *
 * All strings are NUL-terminated, fixed-size, UTF-8. All functions return
 * TPUINFO_OK (0) on success or a negative tpuinfo_status error.
 */

#ifndef TPUINFO_H_
#define TPUINFO_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define TPUINFO_MAX_STR 96
#define TPUINFO_MAX_CHIPS 64

typedef enum {
  TPUINFO_OK = 0,
  TPUINFO_ERR_NOT_FOUND = -1,
  TPUINFO_ERR_IO = -2,
  TPUINFO_ERR_INVALID = -3,
  TPUINFO_ERR_TIMEOUT = -4,
  TPUINFO_ERR_UNSUPPORTED = -5,
} tpuinfo_status;

/* TPU generations (analog of GPU arch / CUDA compute capability). */
typedef enum {
  TPUINFO_GEN_UNKNOWN = 0,
  TPUINFO_GEN_V4 = 4,
  TPUINFO_GEN_V5E = 50,
  TPUINFO_GEN_V5P = 51,
  TPUINFO_GEN_V6E = 60,
} tpuinfo_generation;

typedef struct {
  int32_t index;              /* /dev/accel<index> minor */
  char uuid[TPUINFO_MAX_STR]; /* stable chip identity */
  tpuinfo_generation generation;
  char generation_name[16];   /* "v4", "v5e", "v5p", "v6e" */
  int32_t tensorcore_count;   /* TensorCores on this chip (subslice units) */
  int64_t hbm_bytes;          /* HBM capacity */
  char pci_address[32];       /* domain:bus:dev.fn */
  char driver_version[32];    /* accel driver version */
  /* ICI topology: the (cliqueID, coords) analog. Hosts sharing slice_id are
   * ICI-reachable (one provisioned slice); worker_index is the stable index
   * of this host within the slice (TPU_WORKER_ID source). Empty slice_id
   * means the chip is not part of a provisioned multi-host slice. */
  char slice_id[TPUINFO_MAX_STR];
  int32_t worker_index;
  int32_t coord_x, coord_y, coord_z; /* chip coords within the slice mesh */
  int32_t healthy;            /* 1 = healthy, 0 = unhealthy */
} tpuinfo_chip;

typedef struct {
  int32_t chip_index;  /* -1: affects all chips on the host */
  int32_t code;        /* driver-specific event code (Xid analog) */
  char kind[32];       /* "hbm_ecc", "ici_link_down", "thermal", ... */
  char description[TPUINFO_MAX_STR];
} tpuinfo_event;

typedef struct tpuinfo_ctx tpuinfo_ctx;

/* Open a context against a filesystem root ("" or NULL => "/"). */
tpuinfo_status tpuinfo_init(const char* root, tpuinfo_ctx** out);
void tpuinfo_shutdown(tpuinfo_ctx* ctx);

const char* tpuinfo_version(void);
const char* tpuinfo_status_string(tpuinfo_status s);

/* Enumeration. */
tpuinfo_status tpuinfo_chip_count(tpuinfo_ctx* ctx, int32_t* out);
tpuinfo_status tpuinfo_get_chip(tpuinfo_ctx* ctx, int32_t index, tpuinfo_chip* out);

/* Runtime settings (nvidia-smi compute-policy / compute-mode analog).
 * Writes <root>/sys/class/accel/accel<N>/device/timeslice_us etc. */
tpuinfo_status tpuinfo_set_timeslice(tpuinfo_ctx* ctx, int32_t index, int32_t interval_us);
tpuinfo_status tpuinfo_get_timeslice(tpuinfo_ctx* ctx, int32_t index, int32_t* out);
/* exclusive: 1 => one process may open the chip (EXCLUSIVE_PROCESS analog) */
tpuinfo_status tpuinfo_set_exclusive_mode(tpuinfo_ctx* ctx, int32_t index, int32_t exclusive);

/* Health events: tail-reads appended records from
 * <root>/sys/class/accel/health_events ("<chip> <code> <kind> <desc...>").
 * Blocks up to timeout_ms; returns TPUINFO_ERR_TIMEOUT when none arrived
 * (the NVML eventSet.Wait(5000) loop analog, device_health.go:146-204). */
tpuinfo_status tpuinfo_wait_health_event(tpuinfo_ctx* ctx, int32_t timeout_ms,
                                         tpuinfo_event* out);

#ifdef __cplusplus
}
#endif

#endif /* TPUINFO_H_ */
