// tpu-slice-daemon — per-node ICI-slice rendezvous & readiness daemon.
//
// TPU-native replacement for the nvidia-imex daemon that the reference's
// compute-domain-daemon wraps (cmd/compute-domain-daemon/main.go:41-48,
// 233-234; process.go). IMEX brokers GPU-memory export across NVLink; on
// TPU there is nothing to broker — ICI is wired by slice provisioning — so
// the daemon's job reduces to what the control plane actually consumes:
//
//   1. hold the slice identity (slice_id, worker index) for this host,
//   2. rendezvous with peer daemons listed in a nodes config (the
//      nodes.cfg/DNS analog, re-read on SIGUSR1 like IMEX re-resolves),
//   3. answer a local status query — the `nvidia-imex-ctl -q` READY analog
//      used by startup/liveness probes (main.go:381-405).
//
// Protocol (newline-terminated ASCII over TCP):
//   "Q"                  -> "READY peers=<reachable>/<total>\n" | "NOT_READY ...\n"
//   "H <slice_id> <idx>" -> "OK <my_slice_id> <my_idx>\n"  (peer hello)
//
// Readiness: the daemon is READY once it is serving and has loaded its
// config — matching IMEX-with-DNS-names semantics where daemons start
// eagerly and workload pods release on *local* daemon readiness
// (computedomain.go spec docs; SliceDaemonsWithDNSNames gate). Peer
// reachability is reported, not gated on.
//
// Usage:
//   tpu-slice-daemon --config <file>       run (config: key=value lines)
//   tpu-slice-daemon --check --port <p>    probe localhost; exit 0 iff READY
//
// Config keys: node_ip, port, nodes_config, slice_id, worker_index.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::atomic<bool> g_stop{false};
std::atomic<bool> g_reload{false};

void OnSignal(int sig) {
  if (sig == SIGUSR1) {
    g_reload = true;
  } else {
    g_stop = true;
  }
}

struct Config {
  std::string node_ip = "0.0.0.0";
  int port = 7551;
  std::string nodes_config;
  std::string slice_id;
  int worker_index = 0;
};

bool LoadConfig(const std::string& path, Config* out) {
  std::ifstream f(path);
  if (!f.good()) return false;
  std::string line;
  while (std::getline(f, line)) {
    if (line.empty() || line[0] == '#') continue;
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = line.substr(0, eq), val = line.substr(eq + 1);
    if (key == "node_ip") out->node_ip = val;
    else if (key == "port") out->port = atoi(val.c_str());
    else if (key == "nodes_config") out->nodes_config = val;
    else if (key == "slice_id") out->slice_id = val;
    else if (key == "worker_index") out->worker_index = atoi(val.c_str());
  }
  return true;
}

// Peer list: one "host[:port]" per line (DNS names in the default mode —
// stable compute-domain-daemon-%04d names — or raw IPs in legacy mode).
std::vector<std::string> LoadPeers(const std::string& path) {
  std::vector<std::string> peers;
  std::ifstream f(path);
  std::string line;
  while (std::getline(f, line)) {
    while (!line.empty() && (line.back() == '\r' || line.back() == ' '))
      line.pop_back();
    if (!line.empty() && line[0] != '#') peers.push_back(line);
  }
  return peers;
}

int DialPeer(const std::string& peer, int default_port, int timeout_ms) {
  std::string host = peer;
  int port = default_port;
  auto colon = peer.rfind(':');
  if (colon != std::string::npos && peer.find(':') == colon) {  // not IPv6
    host = peer.substr(0, colon);
    port = atoi(peer.c_str() + colon + 1);
  }
  struct addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res) != 0)
    return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd >= 0) {
    struct timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      close(fd);
      fd = -1;
    }
  }
  freeaddrinfo(res);
  return fd;
}

class Daemon {
 public:
  explicit Daemon(const Config& cfg) : cfg_(cfg) {}

  bool Start() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)cfg_.port);
    addr.sin_addr.s_addr = INADDR_ANY;
    if (bind(listen_fd_, (struct sockaddr*)&addr, sizeof(addr)) != 0) return false;
    if (listen(listen_fd_, 16) != 0) return false;
    ready_ = true;
    server_thread_ = std::thread([this] { Serve(); });
    sweep_thread_ = std::thread([this] { SweepPeers(); });
    return true;
  }

  void Stop() {
    ready_ = false;
    if (listen_fd_ >= 0) {
      shutdown(listen_fd_, SHUT_RDWR);
      close(listen_fd_);
      listen_fd_ = -1;
    }
    if (server_thread_.joinable()) server_thread_.join();
    if (sweep_thread_.joinable()) sweep_thread_.join();
  }

 private:
  void Serve() {
    while (!g_stop) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (g_stop) break;
        // Back off on persistent accept errors (EMFILE) instead of
        // busy-spinning a core.
        usleep(10 * 1000);
        continue;
      }
      // Bound the inbound read the same way outbound dials are bounded
      // (DialPeer sets SO_RCVTIMEO): without this, one idle client — a
      // port scanner, a stalled TCP connection — blocks the accept loop
      // indefinitely, --check probes time out, and the node flaps
      // NotReady even though the daemon is healthy.
      struct timeval tv{1, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      char buf[256];
      ssize_t n = read(fd, buf, sizeof(buf) - 1);
      if (n > 0) {
        buf[n] = '\0';
        std::string reply = Handle(std::string(buf));
        (void)!write(fd, reply.data(), reply.size());
      }
      close(fd);
    }
  }

  std::string Handle(const std::string& req) {
    if (!req.empty() && req[0] == 'Q') {
      std::lock_guard<std::mutex> l(mu_);
      char out[128];
      snprintf(out, sizeof(out), "%s peers=%d/%d\n",
               ready_ ? "READY" : "NOT_READY", reachable_, total_peers_);
      return out;
    }
    if (!req.empty() && req[0] == 'H') {
      char out[160];
      snprintf(out, sizeof(out), "OK %s %d\n", cfg_.slice_id.c_str(),
               cfg_.worker_index);
      return out;
    }
    return "ERR unknown command\n";
  }

  void SweepPeers() {
    while (!g_stop) {
      if (g_reload.exchange(false)) {
        // SIGUSR1: membership changed; re-read immediately (the IMEX
        // re-resolve analog, cd-daemon main.go:368).
      }
      std::vector<std::string> peers;
      if (!cfg_.nodes_config.empty()) peers = LoadPeers(cfg_.nodes_config);
      int ok = 0;
      for (const auto& p : peers) {
        int fd = DialPeer(p, cfg_.port, 500);
        if (fd >= 0) {
          std::string hello = "H " + cfg_.slice_id + " " +
                              std::to_string(cfg_.worker_index) + "\n";
          if (write(fd, hello.data(), hello.size()) > 0) {
            char buf[160];
            ssize_t n = read(fd, buf, sizeof(buf) - 1);
            if (n > 2 && strncmp(buf, "OK", 2) == 0) ++ok;
          }
          close(fd);
        }
      }
      {
        std::lock_guard<std::mutex> l(mu_);
        reachable_ = ok;
        total_peers_ = (int)peers.size();
      }
      for (int i = 0; i < 20 && !g_stop && !g_reload; ++i)
        usleep(100 * 1000);
    }
  }

  Config cfg_;
  // Closed by Stop() while Serve() loops on accept: atomic so the
  // shutdown handoff is not a data race (TSan tier, hack/race.sh).
  std::atomic<int> listen_fd_{-1};
  std::thread server_thread_, sweep_thread_;
  std::mutex mu_;
  // Written by Start()/Stop() on the main thread, read by connection
  // handlers — atomic, not plain (TSan tier finding, hack/race.sh).
  std::atomic<bool> ready_{false};
  int reachable_ = 0;
  int total_peers_ = 0;
};

int RunCheck(int port) {
  int fd = DialPeer("127.0.0.1", port, 1000);
  if (fd < 0) {
    fprintf(stderr, "check: cannot connect to 127.0.0.1:%d\n", port);
    return 1;
  }
  (void)!write(fd, "Q\n", 2);
  char buf[128];
  ssize_t n = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  if (n <= 0) return 1;
  buf[n] = '\0';
  printf("%s", buf);
  return strncmp(buf, "READY", 5) == 0 ? 0 : 1;
}

}  // namespace

int main(int argc, char** argv) {
  std::string config_path;
  bool check = false;
  int check_port = 7551;
  for (int i = 1; i < argc; ++i) {
    if (strcmp(argv[i], "--config") == 0 && i + 1 < argc) {
      config_path = argv[++i];
    } else if (strcmp(argv[i], "--check") == 0) {
      check = true;
    } else if (strcmp(argv[i], "--port") == 0 && i + 1 < argc) {
      check_port = atoi(argv[++i]);
    } else {
      fprintf(stderr,
              "usage: tpu-slice-daemon --config <file> | --check --port <p>\n");
      return 2;
    }
  }
  if (check) return RunCheck(check_port);

  // Handlers before any config I/O (and only on the run path — the
  // --check probe keeps default dispositions so Ctrl-C still kills it):
  // the wrapper's update loop may SIGUSR1 us the moment we exist, and the
  // default disposition for SIGUSR1 is process death. Observed in the
  // wild as "child exited unexpectedly (rc=-10)" during startup
  // (BENCH_r03). Reference keeps the same ordering discipline in its
  // daemon wrapper (cmd/compute-domain-daemon/process.go:170-203).
  signal(SIGTERM, OnSignal);
  signal(SIGINT, OnSignal);
  signal(SIGUSR1, OnSignal);

  if (config_path.empty()) {
    fprintf(stderr, "tpu-slice-daemon: --config required\n");
    return 2;
  }

  Config cfg;
  if (!LoadConfig(config_path, &cfg)) {
    fprintf(stderr, "tpu-slice-daemon: cannot read config %s\n",
            config_path.c_str());
    return 1;
  }

  Daemon d(cfg);
  if (!d.Start()) {
    fprintf(stderr, "tpu-slice-daemon: failed to bind port %d\n", cfg.port);
    return 1;
  }
  fprintf(stderr, "tpu-slice-daemon: serving on port %d (slice_id=%s worker=%d)\n",
          cfg.port, cfg.slice_id.c_str(), cfg.worker_index);
  while (!g_stop) usleep(100 * 1000);
  d.Stop();
  return 0;
}
