// tpuctl — per-chip runtime settings CLI.
//
// The exec seam replacing the reference's `nvidia-smi compute-policy
// --set-timeslice` / `-c EXCLUSIVE_PROCESS` invocations
// (cmd/gpu-kubelet-plugin/nvlib.go:564-601): the kubelet plugin's sharing
// managers exec this binary so runtime settings changes are auditable and
// restartable independent of the plugin process.
//
// Usage:
//   tpuctl list                              enumerate chips (one per line)
//   tpuctl set-timeslice <chip> <usec>       program program-scheduler slice
//   tpuctl get-timeslice <chip>
//   tpuctl set-exclusive <chip> <0|1>        (non-)exclusive process mode
//   tpuctl version
//
// The filesystem root honors TPUINFO_SYSFS_ROOT for tests/fakes.

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "tpuinfo.h"

namespace {

int Fail(tpuinfo_status st, const char* what) {
  fprintf(stderr, "tpuctl: %s: %s\n", what, tpuinfo_status_string(st));
  return 1;
}

int CmdList(tpuinfo_ctx* ctx) {
  int32_t n = 0;
  tpuinfo_status st = tpuinfo_chip_count(ctx, &n);
  if (st != TPUINFO_OK) return Fail(st, "chip_count");
  // Header matches field order consumers parse; keep stable.
  printf("index\tuuid\tgen\tcores\thbm_bytes\tpci\tslice_id\tworker\tcoords\thealthy\n");
  // Indices may be sparse; scan the index space, skipping holes.
  int32_t printed = 0;
  for (int32_t idx = 0; idx < TPUINFO_MAX_CHIPS && printed < n; ++idx) {
    tpuinfo_chip c;
    if (tpuinfo_get_chip(ctx, idx, &c) != TPUINFO_OK) continue;
    printf("%d\t%s\t%s\t%d\t%lld\t%s\t%s\t%d\t%d,%d,%d\t%d\n", c.index, c.uuid,
           c.generation_name, c.tensorcore_count, (long long)c.hbm_bytes,
           c.pci_address, c.slice_id, c.worker_index, c.coord_x, c.coord_y,
           c.coord_z, c.healthy);
    ++printed;
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2 || strcmp(argv[1], "--help") == 0 ||
      strcmp(argv[1], "-h") == 0) {
    fprintf(stderr, "usage: tpuctl <list|set-timeslice|get-timeslice|set-exclusive|version> ...\n");
    return 2;
  }
  if (strcmp(argv[1], "version") == 0) {
    printf("tpuctl %s\n", tpuinfo_version());
    return 0;
  }

  const char* root = getenv("TPUINFO_SYSFS_ROOT");
  tpuinfo_ctx* ctx = nullptr;
  tpuinfo_status st = tpuinfo_init(root, &ctx);
  if (st != TPUINFO_OK) return Fail(st, "init");

  int rc = 2;
  if (strcmp(argv[1], "list") == 0) {
    rc = CmdList(ctx);
  } else if (strcmp(argv[1], "set-timeslice") == 0 && argc == 4) {
    st = tpuinfo_set_timeslice(ctx, atoi(argv[2]), atoi(argv[3]));
    rc = (st == TPUINFO_OK) ? 0 : Fail(st, "set-timeslice");
  } else if (strcmp(argv[1], "get-timeslice") == 0 && argc == 3) {
    int32_t v = 0;
    st = tpuinfo_get_timeslice(ctx, atoi(argv[2]), &v);
    if (st == TPUINFO_OK) {
      printf("%d\n", v);
      rc = 0;
    } else {
      rc = Fail(st, "get-timeslice");
    }
  } else if (strcmp(argv[1], "set-exclusive") == 0 && argc == 4) {
    st = tpuinfo_set_exclusive_mode(ctx, atoi(argv[2]), atoi(argv[3]));
    rc = (st == TPUINFO_OK) ? 0 : Fail(st, "set-exclusive");
  } else {
    fprintf(stderr, "tpuctl: unknown or malformed command '%s'\n", argv[1]);
  }
  tpuinfo_shutdown(ctx);
  return rc;
}
