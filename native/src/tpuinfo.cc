// libtpuinfo implementation. See include/tpuinfo.h for the ABI contract and
// the mapping to the reference driver's NVML usage.

#include "tpuinfo.h"

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <mutex>
#include <sstream>
#include <string>
#include <vector>

namespace {

constexpr const char* kVersion = "0.1.0";

struct GenSpec {
  tpuinfo_generation gen;
  const char* name;
  int32_t cores;
  int64_t hbm_bytes;
};

// Generation table: TensorCores per chip and HBM capacity.
// v4: 2 cores / 32 GiB; v5e: 1 core / 16 GiB; v5p: 2 cores / 95 GiB;
// v6e (Trillium): 1 core / 32 GiB.
const GenSpec kGenTable[] = {
    {TPUINFO_GEN_V4, "v4", 2, 32LL << 30},
    {TPUINFO_GEN_V5E, "v5e", 1, 16LL << 30},
    {TPUINFO_GEN_V5P, "v5p", 2, 95LL << 30},
    {TPUINFO_GEN_V6E, "v6e", 1, 32LL << 30},
};

const GenSpec* LookupGen(const std::string& name) {
  for (const auto& g : kGenTable) {
    if (name == g.name) return &g;
  }
  return nullptr;
}

bool ReadFileTrimmed(const std::string& path, std::string* out) {
  std::ifstream f(path);
  if (!f.good()) return false;
  std::stringstream ss;
  ss << f.rdbuf();
  std::string s = ss.str();
  while (!s.empty() && (s.back() == '\n' || s.back() == ' ' || s.back() == '\t'))
    s.pop_back();
  *out = s;
  return true;
}

bool WriteFile(const std::string& path, const std::string& content) {
  std::ofstream f(path, std::ios::trunc);
  if (!f.good()) return false;
  f << content;
  return f.good();
}

void CopyStr(char* dst, size_t cap, const std::string& src) {
  snprintf(dst, cap, "%s", src.c_str());
}

}  // namespace

struct tpuinfo_ctx {
  std::string root;              // filesystem root ("" => "/")
  std::string accel_class;       // <root>/sys/class/accel
  std::vector<int32_t> indices;  // discovered chip indices, sorted
  off_t events_offset = 0;       // tail position in health_events
  std::mutex mu;

  std::string DevPath(int32_t idx) const {
    return root + "/dev/accel" + std::to_string(idx);
  }
  std::string ChipDir(int32_t idx) const {
    return accel_class + "/accel" + std::to_string(idx) + "/device";
  }
};

extern "C" {

const char* tpuinfo_version(void) { return kVersion; }

const char* tpuinfo_status_string(tpuinfo_status s) {
  switch (s) {
    case TPUINFO_OK: return "ok";
    case TPUINFO_ERR_NOT_FOUND: return "not found";
    case TPUINFO_ERR_IO: return "i/o error";
    case TPUINFO_ERR_INVALID: return "invalid argument";
    case TPUINFO_ERR_TIMEOUT: return "timeout";
    case TPUINFO_ERR_UNSUPPORTED: return "unsupported";
  }
  return "unknown";
}

tpuinfo_status tpuinfo_init(const char* root, tpuinfo_ctx** out) {
  if (out == nullptr) return TPUINFO_ERR_INVALID;
  auto* ctx = new tpuinfo_ctx();
  ctx->root = (root == nullptr || root[0] == '\0') ? "" : std::string(root);
  // Normalize: strip one trailing slash so path joins are uniform.
  if (!ctx->root.empty() && ctx->root.back() == '/') ctx->root.pop_back();
  ctx->accel_class = ctx->root + "/sys/class/accel";

  DIR* d = opendir(ctx->accel_class.c_str());
  if (d == nullptr) {
    delete ctx;
    return TPUINFO_ERR_NOT_FOUND;
  }
  struct dirent* ent;
  while ((ent = readdir(d)) != nullptr) {
    const char* name = ent->d_name;
    if (strncmp(name, "accel", 5) != 0) continue;
    char* endp = nullptr;
    long idx = strtol(name + 5, &endp, 10);
    if (endp == name + 5 || *endp != '\0') continue;
    // A chip is real only if its char device exists too (the kubelet plugin
    // must never advertise a chip a container cannot be handed).
    struct stat st;
    if (stat(ctx->DevPath((int32_t)idx).c_str(), &st) != 0) continue;
    ctx->indices.push_back((int32_t)idx);
  }
  closedir(d);
  std::sort(ctx->indices.begin(), ctx->indices.end());
  // Start tailing health events at the current end: events predating driver
  // startup are stale (mirrors registering for NVML events at startup).
  struct stat st;
  if (stat((ctx->accel_class + "/health_events").c_str(), &st) == 0) {
    ctx->events_offset = st.st_size;
  }
  *out = ctx;
  return TPUINFO_OK;
}

void tpuinfo_shutdown(tpuinfo_ctx* ctx) { delete ctx; }

tpuinfo_status tpuinfo_chip_count(tpuinfo_ctx* ctx, int32_t* out) {
  if (ctx == nullptr || out == nullptr) return TPUINFO_ERR_INVALID;
  *out = (int32_t)ctx->indices.size();
  return TPUINFO_OK;
}

tpuinfo_status tpuinfo_get_chip(tpuinfo_ctx* ctx, int32_t index, tpuinfo_chip* out) {
  if (ctx == nullptr || out == nullptr) return TPUINFO_ERR_INVALID;
  bool known = false;
  for (int32_t i : ctx->indices) known = known || (i == index);
  if (!known) return TPUINFO_ERR_NOT_FOUND;

  memset(out, 0, sizeof(*out));
  out->index = index;
  const std::string dir = ctx->ChipDir(index);

  std::string gen_name;
  if (!ReadFileTrimmed(dir + "/generation", &gen_name)) return TPUINFO_ERR_IO;
  const GenSpec* spec = LookupGen(gen_name);
  out->generation = spec ? spec->gen : TPUINFO_GEN_UNKNOWN;
  CopyStr(out->generation_name, sizeof(out->generation_name), gen_name);

  std::string s;
  if (ReadFileTrimmed(dir + "/uuid", &s)) {
    CopyStr(out->uuid, sizeof(out->uuid), s);
  } else {
    // Synthesized stable identity when the driver exposes none.
    CopyStr(out->uuid, sizeof(out->uuid),
            "tpu-" + gen_name + "-" + std::to_string(index));
  }
  out->tensorcore_count = spec ? spec->cores : 1;
  if (ReadFileTrimmed(dir + "/tensorcore_count", &s))
    out->tensorcore_count = (int32_t)strtol(s.c_str(), nullptr, 10);
  out->hbm_bytes = spec ? spec->hbm_bytes : 0;
  if (ReadFileTrimmed(dir + "/hbm_bytes", &s))
    out->hbm_bytes = strtoll(s.c_str(), nullptr, 10);
  if (ReadFileTrimmed(dir + "/pci_address", &s))
    CopyStr(out->pci_address, sizeof(out->pci_address), s);
  if (ReadFileTrimmed(dir + "/driver_version", &s))
    CopyStr(out->driver_version, sizeof(out->driver_version), s);
  else
    CopyStr(out->driver_version, sizeof(out->driver_version), "unknown");

  // Topology block (cliqueID/fabric-info analog, cd-plugin nvlib.go:187-258).
  if (ReadFileTrimmed(dir + "/topology/slice_id", &s))
    CopyStr(out->slice_id, sizeof(out->slice_id), s);
  if (ReadFileTrimmed(dir + "/topology/worker_index", &s))
    out->worker_index = (int32_t)strtol(s.c_str(), nullptr, 10);
  if (ReadFileTrimmed(dir + "/topology/coords", &s)) {
    // "x,y,z"
    sscanf(s.c_str(), "%d,%d,%d", &out->coord_x, &out->coord_y, &out->coord_z);
  }

  out->healthy = 1;
  if (ReadFileTrimmed(dir + "/health", &s) && s != "ok" && s != "healthy")
    out->healthy = 0;
  return TPUINFO_OK;
}

tpuinfo_status tpuinfo_set_timeslice(tpuinfo_ctx* ctx, int32_t index,
                                     int32_t interval_us) {
  if (ctx == nullptr || interval_us < 0) return TPUINFO_ERR_INVALID;
  tpuinfo_chip chip;
  tpuinfo_status st = tpuinfo_get_chip(ctx, index, &chip);
  if (st != TPUINFO_OK) return st;
  if (!WriteFile(ctx->ChipDir(index) + "/timeslice_us",
                 std::to_string(interval_us)))
    return TPUINFO_ERR_IO;
  return TPUINFO_OK;
}

tpuinfo_status tpuinfo_get_timeslice(tpuinfo_ctx* ctx, int32_t index, int32_t* out) {
  if (ctx == nullptr || out == nullptr) return TPUINFO_ERR_INVALID;
  std::string s;
  if (!ReadFileTrimmed(ctx->ChipDir(index) + "/timeslice_us", &s))
    return TPUINFO_ERR_NOT_FOUND;
  *out = (int32_t)strtol(s.c_str(), nullptr, 10);
  return TPUINFO_OK;
}

tpuinfo_status tpuinfo_set_exclusive_mode(tpuinfo_ctx* ctx, int32_t index,
                                          int32_t exclusive) {
  if (ctx == nullptr) return TPUINFO_ERR_INVALID;
  tpuinfo_chip chip;
  tpuinfo_status st = tpuinfo_get_chip(ctx, index, &chip);
  if (st != TPUINFO_OK) return st;
  if (!WriteFile(ctx->ChipDir(index) + "/exclusive_mode",
                 exclusive ? "1" : "0"))
    return TPUINFO_ERR_IO;
  return TPUINFO_OK;
}

tpuinfo_status tpuinfo_wait_health_event(tpuinfo_ctx* ctx, int32_t timeout_ms,
                                         tpuinfo_event* out) {
  if (ctx == nullptr || out == nullptr) return TPUINFO_ERR_INVALID;
  std::lock_guard<std::mutex> lock(ctx->mu);
  const std::string path = ctx->accel_class + "/health_events";
  const int poll_step_ms = 20;
  int waited = 0;
  for (;;) {
    struct stat st;
    if (stat(path.c_str(), &st) == 0 && st.st_size > ctx->events_offset) {
      std::ifstream f(path);
      if (!f.good()) return TPUINFO_ERR_IO;
      f.seekg(ctx->events_offset);
      std::string line;
      while (std::getline(f, line)) {
        ctx->events_offset += (off_t)line.size() + 1;
        if (line.empty()) continue;
        // "<chip> <code> <kind> <description...>"
        std::istringstream ls(line);
        int chip_index = -1, code = 0;
        std::string kind, desc;
        ls >> chip_index >> code >> kind;
        std::getline(ls, desc);
        if (!desc.empty() && desc[0] == ' ') desc.erase(0, 1);
        memset(out, 0, sizeof(*out));
        out->chip_index = chip_index;
        out->code = code;
        CopyStr(out->kind, sizeof(out->kind), kind);
        CopyStr(out->description, sizeof(out->description), desc);
        return TPUINFO_OK;
      }
    }
    if (waited >= timeout_ms) return TPUINFO_ERR_TIMEOUT;
    usleep(poll_step_ms * 1000);
    waited += poll_step_ms;
  }
}

}  // extern "C"
