// tpu-multiprocess-coordinator — per-claim multi-tenant chip arbiter.
//
// TPU-native replacement for the nvidia-cuda-mps-control daemon that the
// reference's MPS sharing runs per claim (templates/mps-control-daemon
// .tmpl.yaml:27-42, lifecycle cmd/gpu-kubelet-plugin/sharing.go:191-412).
// MPS arbitrates concurrent CUDA processes on one GPU through a pipe
// directory plus per-client thread/memory limits; libtpu has no vendor
// arbiter, so this daemon IS the arbiter for concurrent libtpu processes
// sharing a chip:
//
//   1. own the claim's coordination directory (the hostPath the kubelet
//      plugin created and the CDI spec bind-mounts into every tenant):
//      create pipe/ and log/, write limits.env with the per-tenant
//      premapped-HBM and TensorCore-percentage caps tenants must honor,
//   2. arbitrate tenant leases over a Unix socket in pipe/ — tenants
//      register with their pid, the coordinator enforces max concurrency
//      and reaps leases whose process died,
//   3. answer the readiness probe (`--check`) the Deployment's
//      startup/readiness probes and the plugin's AssertReady use — the
//      "startup complete" startup.log analog of the reference template.
//
// Protocol (newline-terminated ASCII over the Unix socket):
//   "Q"          -> "READY clients=<n>/<max>\n" | "NOT_READY ...\n"
//   "R <pid>"    -> "OK <lease_id>\n" | "DENIED max-clients\n"
//   "U <lease>"  -> "OK\n" (idempotent)
//   "L"          -> "LEASES <lease>:<pid> ...\n"
//
// A lease is CONNECTION-SCOPED: it lives while the tenant holds the
// socket connection that registered it and is reaped on EOF/error — the
// same liveness contract MPS clients get from their control pipe. This is
// deliberate: tenants run in other pods, so their pids are meaningless in
// the coordinator's PID namespace and kill(pid,0)-style liveness probes
// cannot work; connection lifetime is the only namespace-proof signal.
// The <pid> is recorded for the operator log only.
//
// Usage:
//   tpu-multiprocess-coordinator --dir <coord-dir> [--chips 0,1]
//       [--hbm-limit-map uuid=bytes,...] [--tensorcore-pct N]
//       [--max-clients N]
//   tpu-multiprocess-coordinator --check --dir <coord-dir>
//
// Every accepted connection carries a receive timeout so an idle or
// hostile client can never wedge the serve loop (the probe robustness
// posture of cmd/compute-domain-daemon/main.go:381-405).

#include <errno.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <fstream>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::atomic<bool> g_stop{false};

void OnSignal(int) { g_stop = true; }

struct Options {
  std::string dir;
  std::string chips;          // "0,1" — exported to tenants verbatim
  std::string hbm_limit_map;  // "uuid=bytes,..." — per-chip premapped caps
  int tensorcore_pct = -1;    // -1 = unset
  int max_clients = 16;
};

std::string SocketPath(const std::string& dir) {
  return dir + "/pipe/coordinator.sock";
}

// AF_UNIX sun_path is 108 bytes; coordination dirs can be arbitrarily deep
// (hostPath roots, test tmpdirs). Bind/connect via a relative path from a
// temporary chdir so the daemon works regardless of path length. The chdir
// window is confined to startup / one-shot probe setup, before any other
// thread exists.
class ScopedChdir {
 public:
  explicit ScopedChdir(const std::string& to) {
    ok_ = getcwd(prev_, sizeof(prev_)) != nullptr && chdir(to.c_str()) == 0;
  }
  ~ScopedChdir() {
    if (ok_) (void)!chdir(prev_);
  }
  bool ok() const { return ok_; }

 private:
  char prev_[4096];
  bool ok_ = false;
};

class Log {
 public:
  explicit Log(const std::string& path) : f_(fopen(path.c_str(), "a")) {}
  ~Log() {
    if (f_) fclose(f_);
  }
  // Called from the main thread AND every connection thread: needs its
  // own lock (shared FILE*) and gmtime_r (gmtime's static buffer is a
  // data race — found by the TSan tier, hack/race.sh).
  void Line(const char* fmt, ...) {
    char msg[512];
    va_list ap;
    va_start(ap, fmt);
    vsnprintf(msg, sizeof(msg), fmt, ap);
    va_end(ap);
    time_t now = time(nullptr);
    struct tm tm_buf {};
    char ts[32];
    strftime(ts, sizeof(ts), "%Y-%m-%dT%H:%M:%S", gmtime_r(&now, &tm_buf));
    std::lock_guard<std::mutex> l(mu_);
    if (f_) {
      fprintf(f_, "%s %s\n", ts, msg);
      fflush(f_);
    }
    fprintf(stderr, "tpu-multiprocess-coordinator: %s\n", msg);
  }

 private:
  std::mutex mu_;
  FILE* f_;
};

class Coordinator {
 public:
  Coordinator(const Options& opts, Log* log) : opts_(opts), log_(log) {}

  bool Start() {
    if (mkdir((opts_.dir + "/pipe").c_str(), 0755) != 0 && errno != EEXIST)
      return false;
    if (mkdir((opts_.dir + "/log").c_str(), 0755) != 0 && errno != EEXIST)
      return false;
    if (!WriteLimitsEnv()) return false;

    unlink(SocketPath(opts_.dir).c_str());  // stale crashed predecessor
    listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    struct sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, "coordinator.sock", sizeof(addr.sun_path) - 1);
    {
      ScopedChdir cd(opts_.dir + "/pipe");
      if (!cd.ok()) return false;
      if (bind(listen_fd_, (struct sockaddr*)&addr, sizeof(addr)) != 0)
        return false;
    }
    if (listen(listen_fd_, 16) != 0) return false;

    serve_thread_ = std::thread([this] { Serve(); });

    // Startup marker last — only after the socket answers (the reference
    // writes startup.log after the daemon accepted its settings).
    std::ofstream marker(opts_.dir + "/log/startup.log");
    marker << "startup complete\n";
    ready_ = true;
    return true;
  }

  void Stop() {
    ready_ = false;
    unlink((opts_.dir + "/log/startup.log").c_str());
    if (listen_fd_ >= 0) {
      shutdown(listen_fd_, SHUT_RDWR);
      close(listen_fd_);
      listen_fd_ = -1;
    }
    if (serve_thread_.joinable()) serve_thread_.join();
    // Connection threads are detached; they observe g_stop within their
    // 1s receive-timeout tick. Bound the wait so Stop() cannot hang on a
    // wedged client.
    for (int i = 0; i < 50 && active_conns_ > 0; ++i) usleep(100 * 1000);
    unlink(SocketPath(opts_.dir).c_str());
  }

 private:
  // limits.env is the published contract: every tenant container has this
  // directory bind-mounted (CDI edit) and must honor these caps. The
  // kubelet plugin passes the same values into the claim's CDI env, so
  // file and env always agree — the file is the arbiter's copy tenants
  // can re-read after coordinator restarts.
  bool WriteLimitsEnv() {
    std::ofstream f(opts_.dir + "/limits.env");
    if (!f.good()) return false;
    f << "# Written by tpu-multiprocess-coordinator; tenants must honor\n";
    f << "# these caps when initializing libtpu.\n";
    if (!opts_.chips.empty()) f << "TPU_VISIBLE_CHIPS=" << opts_.chips << "\n";
    if (!opts_.hbm_limit_map.empty())
      f << "TPU_HBM_LIMIT_MAP=" << opts_.hbm_limit_map << "\n";
    if (opts_.tensorcore_pct >= 0)
      f << "TPU_TENSORCORE_PERCENTAGE=" << opts_.tensorcore_pct << "\n";
    f << "TPU_MULTIPROCESS_MAX_CLIENTS=" << opts_.max_clients << "\n";
    return f.good();
  }

  void Serve() {
    while (!g_stop) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (g_stop) break;
        // Persistent accept errors (EMFILE under fd exhaustion) must not
        // busy-spin a full core and starve the connection threads whose
        // completion would free fds.
        usleep(10 * 1000);
        continue;
      }
      // One thread per connection: probes are one-shot, but a tenant
      // holds its connection for the lifetime of its lease, and an idle
      // or hostile client must never delay other connections' probes.
      // The receive timeout only paces the g_stop check.
      struct timeval tv{1, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      // Detached + counted rather than joined: probe connections are
      // frequent (kubelet execs --check every few seconds) and a grow-only
      // thread list would leak; Stop() waits on the counter instead.
      ++active_conns_;
      std::thread([this, fd] { HandleConnection(fd); }).detach();
    }
  }

  void HandleConnection(int fd) {
    int lease_id = -1;  // lease registered by THIS connection, if any
    char buf[256];
    while (!g_stop) {
      ssize_t n = read(fd, buf, sizeof(buf) - 1);
      if (n == 0) break;  // EOF: tenant went away
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) continue;  // pace tick
        break;
      }
      buf[n] = '\0';
      std::string reply = Handle(std::string(buf), &lease_id);
      if (write(fd, reply.data(), reply.size()) < 0) break;
    }
    // Connection-scoped liveness: whatever this connection registered is
    // reaped the moment the connection dies, however the tenant exited.
    if (lease_id >= 0) {
      std::lock_guard<std::mutex> l(mu_);
      if (leases_.erase(lease_id))
        log_->Line("reap lease %d: connection closed (%zu/%d)", lease_id,
                   leases_.size(), opts_.max_clients);
    }
    close(fd);
    --active_conns_;
  }

  std::string Handle(const std::string& req, int* conn_lease) {
    std::istringstream in(req);
    std::string cmd;
    in >> cmd;
    std::lock_guard<std::mutex> l(mu_);
    if (cmd == "Q") {
      char out[128];
      snprintf(out, sizeof(out), "%s clients=%zu/%d\n",
               ready_ ? "READY" : "NOT_READY", leases_.size(),
               opts_.max_clients);
      return out;
    }
    if (cmd == "R") {
      long pid = 0;
      in >> pid;
      if (pid <= 0) return "ERR bad pid\n";
      if (*conn_lease >= 0) return "ERR lease already held\n";
      if ((int)leases_.size() >= opts_.max_clients) {
        log_->Line("deny tenant pid=%ld: max-clients %d reached", pid,
                   opts_.max_clients);
        return "DENIED max-clients\n";
      }
      int id = next_lease_++;
      leases_[id] = (pid_t)pid;
      *conn_lease = id;
      log_->Line("lease %d granted to pid %ld (%zu/%d)", id, pid,
                 leases_.size(), opts_.max_clients);
      char out[64];
      snprintf(out, sizeof(out), "OK %d\n", id);
      return out;
    }
    if (cmd == "U") {
      int id = -1;
      in >> id;
      if (id < 0) return "ERR bad id\n";
      // A connection may only release ITS OWN lease: tenants are mutually
      // untrusted processes, and honoring arbitrary ids would let one
      // tenant free another's slot and over-admit past max_clients.
      // Idempotent for the holder (repeat "U" after release is OK).
      if (id != *conn_lease) {
        if (leases_.count(id)) return "ERR not lease holder\n";
        return "OK\n";  // already gone (or never existed): idempotent
      }
      leases_.erase(id);
      *conn_lease = -1;
      log_->Line("lease %d released (%zu/%d)", id, leases_.size(),
                 opts_.max_clients);
      return "OK\n";
    }
    if (cmd == "L") {
      std::ostringstream out;
      out << "LEASES";
      for (const auto& kv : leases_) out << " " << kv.first << ":" << kv.second;
      out << "\n";
      return out.str();
    }
    return "ERR unknown command\n";
  }

  Options opts_;
  Log* log_;
  // Closed by Stop() while Serve() loops on accept: atomic so the
  // shutdown handoff is not a data race (TSan tier, hack/race.sh).
  std::atomic<int> listen_fd_{-1};
  std::thread serve_thread_;
  std::atomic<int> active_conns_{0};
  std::mutex mu_;
  std::atomic<bool> ready_{false};
  std::map<int, pid_t> leases_;
  int next_lease_ = 1;
};

int DialSocket(const std::string& pipe_dir, int timeout_ms) {
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  struct sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, "coordinator.sock", sizeof(addr.sun_path) - 1);
  ScopedChdir cd(pipe_dir);
  if (!cd.ok() || connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int RunCheck(const std::string& dir) {
  int fd = DialSocket(dir + "/pipe", 1000);
  if (fd < 0) {
    fprintf(stderr, "check: cannot connect to %s\n", SocketPath(dir).c_str());
    return 1;
  }
  (void)!write(fd, "Q\n", 2);
  char buf[128];
  ssize_t n = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  if (n <= 0) return 1;
  buf[n] = '\0';
  printf("%s", buf);
  return strncmp(buf, "READY", 5) == 0 ? 0 : 1;
}

}  // namespace

int main(int argc, char** argv) {
  Options opts;
  bool check = false;
  for (int i = 1; i < argc; ++i) {
    if (strcmp(argv[i], "--dir") == 0 && i + 1 < argc) {
      opts.dir = argv[++i];
    } else if (strcmp(argv[i], "--chips") == 0 && i + 1 < argc) {
      opts.chips = argv[++i];
    } else if (strcmp(argv[i], "--hbm-limit-map") == 0 && i + 1 < argc) {
      opts.hbm_limit_map = argv[++i];
    } else if (strcmp(argv[i], "--tensorcore-pct") == 0 && i + 1 < argc) {
      opts.tensorcore_pct = atoi(argv[++i]);
    } else if (strcmp(argv[i], "--max-clients") == 0 && i + 1 < argc) {
      opts.max_clients = atoi(argv[++i]);
    } else if (strcmp(argv[i], "--check") == 0) {
      check = true;
    } else {
      fprintf(stderr,
              "usage: tpu-multiprocess-coordinator --dir <d> [--chips c]\n"
              "           [--hbm-limit-map m] [--tensorcore-pct n]\n"
              "           [--max-clients n]\n"
              "       tpu-multiprocess-coordinator --check --dir <d>\n");
      return 2;
    }
  }
  if (opts.dir.empty()) {
    fprintf(stderr, "tpu-multiprocess-coordinator: --dir required\n");
    return 2;
  }
  if (check) return RunCheck(opts.dir);

  signal(SIGTERM, OnSignal);
  signal(SIGINT, OnSignal);
  signal(SIGPIPE, SIG_IGN);

  // pipe/ and log/ may not exist yet when the pod starts before the
  // kubelet plugin finished its mkdirs; create them before opening the
  // log file (Start() re-checks them).
  mkdir(opts.dir.c_str(), 0755);
  mkdir((opts.dir + "/pipe").c_str(), 0755);
  mkdir((opts.dir + "/log").c_str(), 0755);
  // Heap-allocated and never freed ON PURPOSE: connection threads are
  // detached, and Stop()'s drain wait is bounded — a client wedged in
  // write() can still touch the Coordinator/Log after Stop() returns.
  // Leaking both keeps every reachable object valid until _exit; the OS
  // reclaims at process teardown (this is the whole process's lifetime).
  Log* log = new Log(opts.dir + "/log/coordinator.log");
  Coordinator* c = new Coordinator(opts, log);
  if (!c->Start()) {
    fprintf(stderr,
            "tpu-multiprocess-coordinator: failed to start in %s: %s\n",
            opts.dir.c_str(), strerror(errno));
    return 1;
  }
  log->Line("serving on %s (chips=%s max_clients=%d)",
            SocketPath(opts.dir).c_str(), opts.chips.c_str(),
            opts.max_clients);
  while (!g_stop) usleep(100 * 1000);
  c->Stop();
  log->Line("stopped");
  // _exit, not return: a plain return runs exit()'s stdio teardown, which
  // fcloses the leaked Log's FILE* — exactly what a still-wedged detached
  // connection thread must not observe. _exit keeps every leaked object
  // (and stream) intact until the process is gone.
  _exit(0);
}
