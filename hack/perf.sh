#!/usr/bin/env bash
# Perf tier: the claim-to-ready hot path's regression tripwires (ISSUE 2):
#
#   hack/perf.sh [CYCLES]
#
# 1. The group-commit tripwire tests (tests/test_batch_prepare.py): a
#    batched prepare/unprepare of N claims must land exactly ONE
#    terminal checkpoint store / device sync (asserted against the
#    CheckpointManager store counters) — N syncs means the group commit
#    silently degraded back to per-claim commits.
# 2. A quick claim-to-ready probe through the real gRPC path (single
#    claim p50 + batched per-claim p50 on a fake 4-chip v5p inventory),
#    printed as one JSON line for eyeballing against BENCH_r*.json.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
CYCLES="${1:-${PERF_CYCLES:-30}}"

echo ">> group-commit tripwire (one terminal sync per batch)"
JAX_PLATFORMS=cpu python -m pytest "$REPO_ROOT/tests/test_batch_prepare.py" \
  -q -p no:cacheprovider

echo ">> claim-to-ready probe (${CYCLES} cycles, fake v5p 4-chip)"
cd "$REPO_ROOT"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake python - "$CYCLES" <<'EOF'
import json
import statistics
import sys

from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips

import bench

n = int(sys.argv[1])
bd = bench._BenchDriver(FakeBackend(default_fake_chips(4, "v5p")),
                        prefix="tpu-dra-perf-")
try:
    for i in range(5):
        bd.cycle(f"warm-{i}")
    p50_one = bd.config_p50("one", n, devices=[f"chip-{bd.chips[0]}"])
    breakdown = {}
    bd.batch_cycle("bwarm", 4)
    p50_batch = statistics.median(sorted(
        bd.batch_cycle(f"b{i}", 4, breakdown=breakdown)
        for i in range(n)))
    out = {
        "claim_to_ready_p50_1chip_ms": round(p50_one, 3),
        "claim_to_ready_p50_batch_per_claim_ms": round(p50_batch, 3),
        "batch_amortization_x": round(p50_one / p50_batch, 2),
        "terminal_stores": bd.state._ckpt_mgr.terminal_stores,
        "slot_syncs": bd.state._ckpt_mgr.slot_syncs,
    }
    for k, vals in sorted(breakdown.items()):
        if k != "n_claims":
            out[f"batch_{k}_ms"] = round(statistics.median(vals), 4)
finally:
    bd.close()
print(json.dumps(out))
if p50_batch >= p50_one:
    sys.exit("REGRESSION: batched per-claim p50 not below single-claim p50")
EOF
echo ">> perf tier green"
