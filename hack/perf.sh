#!/usr/bin/env bash
# Perf tier: the claim-to-ready hot path's regression tripwires (ISSUE 2)
# plus the event-driven control plane's gates (ISSUE 3):
#
#   hack/perf.sh [CYCLES]
#
# 1. The group-commit tripwire tests (tests/test_batch_prepare.py): a
#    batched prepare/unprepare of N claims must land exactly ONE
#    terminal journal append / group sync (asserted against the
#    CheckpointManager journal counters) — N appends means the group
#    commit silently degraded back to per-claim commits.
# 2. A claim-to-ready probe through the FRAMED fast transport (the
#    prepare transport since the ISSUE 15 swap, SURVEY §21): single
#    claim p50 + batched per-claim p50 on a fake 4-chip v5p inventory +
#    batch-64 on a 64-chip one, printed as one JSON line for eyeballing
#    against BENCH_r*.json — gated on: single-claim p50 under
#    PERF_P50_GATE_MS (ISSUE 17: default DERIVED from this box's
#    measured physics — hack/fsync_probe.py — as
#    7*cpu_ref + 2*fdatasync_floor; ~0.6 on a desktop-class core, see
#    the derivation block below; an explicit env value still wins.
#    The storage engine owns the 7-cpu-ref software allowance: binary
#    journal framing + CDI template cache replaced the per-record
#    JSON that used to dominate the post-fdatasync residual), plus a
#    group-commit-window-never-holds-idle tripwire (the probe is
#    sequential, so journal_window_holds must stay 0),
#    TRANSPORT residual (client p50 minus server handler p50) under
#    PERF_TRANSPORT_GATE_MS (default max(0.35, 1.6*cpu_ref); measured
#    ~0.15-0.25 framed vs ~0.5-0.7 over sync gRPC — the lever ROADMAP
#    item 5 named, now gated so it cannot silently regrow), and
#    batch-64 per-claim under PERF_BATCH64_GATE_MS (default
#    max(0.3, 1.4*cpu_ref); measures ~0.2-0.27).
# 2b. Sustained-load phase (ISSUE 15): PERF_SUSTAINED_S seconds
#    (default 25; BENCH recording rounds run minutes via
#    TPU_DRA_BENCH_SUSTAINED_S) of mixed-batch prepare/unprepare from 8
#    framed connections flat-out through one node. Gates: achieved RPC
#    rate >= PERF_SUSTAINED_RPS_MIN (since ISSUE 17 the default is
#    host-budgeted: 4000 on >= 4-core hosts, 800/core below that — a
#    single-core container serializes the whole closed loop onto one
#    core; was a flat 1000), zero RPC errors and
#    zero leaked claims, single-claim p99-under-load <=
#    PERF_SUSTAINED_P99_GATE_MS (default 30), the pipeline in-flight
#    window respected (peak <= 16), and the journal sync-coalescing
#    ratio measured AT DEPTH: with >= 8 RPCs in flight the barrier
#    queue is provably full, so coalescing is deterministic —
#    appends/group-syncs >= PERF_COALESCE_RATIO_MIN (since ISSUE 17's
#    adaptive group-commit window made coalescing engineered rather
#    than opportunistic the default is host-budgeted: 4.0 on >= 4-core
#    hosts, 2.5 below — one core caps co-committers in flight; was a
#    flat 1.5 measuring ~2.5)
#    with no retry loop (the old idle-probe gate retried 5 rounds
#    because coalescing was opportunistic there).
# 2c. Hot-restart phase (ISSUE 16, SURVEY §22): the kubelet plugin is
#    restarted PERF_RESTARTS times mid-stream under framed churn —
#    gated on ZERO failed RPCs (drain + journal recovery + client
#    retry-on-reconnect mask the gap entirely), zero leaked claims,
#    and the drain window under PERF_DRAIN_GATE_S.
# 2d. Scheduler failover phase (ISSUE 16): active-standby HA takeover
#    under pod churn — lease-expiry-to-first-new-allocation p50 gated
#    under PERF_FAILOVER_P50_GATE_MS (tripwire; the 0.4s lease expiry
#    wait dominates by design).
# 3. Scheduler churn gates on the fake backend (SCHED_NODES x
#    SCHED_PODS, defaults 100x500): steady-state full relists MUST be 0
#    (event-driven, not poll-and-scan), CEL compiles MUST not exceed
#    distinct selector sources (compile cache), claim GC must drain, and
#    the pod-to-allocated p50 must not regress >50% against the newest
#    BENCH_r*.json round that recorded it.
# 3b. Tracing-overhead gates (ISSUE 13, SURVEY §19): the claim-to-ready
#    probe alternates tracing-off/-on PER CYCLE (both populations share
#    one time window, so 1-core CI drift cancels) and the scheduler
#    churn alternates whole passes best-of-3 per mode; both fail when
#    enabling tracing moves claim_to_ready_p50 /
#    sched_throughput_pods_per_s by more than TRACE_OVERHEAD_PCT
#    (default 5%, + a small absolute slack on the ~1ms p50).
# 4. SCALED churn gates (ISSUE 8, parallel scheduler core; SURVEY §15)
#    at SCHED_SCALED_NODES x SCHED_SCALED_PODS (defaults 1000x5000):
#    against the r05 single-worker scheduler measured at the SAME size
#    in this environment (SCHED_SCALED_BASELINE_PPS/P50_MS), the
#    single-worker pass must deliver >= 2x throughput and <= 2x p50
#    (the core's speed: snapshot scans, busy-node skip, candidate
#    caching, nudge-set fix, cheap fake-apiserver copies), the
#    default-pool pass must not regress below 1x (GIL-bound CPython
#    gains nothing from extra sim workers — the pool is the
#    concurrency substrate, chaos-verified at workers=4), and full
#    relists must be 0 in both.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
CYCLES="${1:-${PERF_CYCLES:-30}}"

# Budget the latency gates against THIS box's measured physics (ISSUE
# 17: absolute gates trip on slower hosts — the PR 16 finding). TWO
# probe terms, because the hot path has two kinds of cost:
#  - PERF_FSYNC_FLOOR_MS: the storage device. hack/fsync_probe.py
#    times the exact in-place pwrite+fdatasync the journal's
#    group-sync leader performs.
#  - PERF_CPU_REF_MS: the core. fsync_probe --cpu times a fixed
#    serialization-shaped Python workload (min-of-samples, so
#    scheduler noise is excluded). A floor-only budget still tripped
#    on a host whose core ran the identical hot path ~1.7x slower
#    than the box that calibrated the old absolute 1.0ms gate (A/B'd
#    HEAD-vs-change at equal numbers to prove it was the box).
# The claim-to-ready budget is then pipeline-shaped, not absolute:
# ~7 cpu-refs of decode/state/span/CDI/framing work the engine is
# accountable for, plus two sync floors (one sync + jitter headroom).
# On a desktop-class core (cpu_ref ~0.07ms, NVMe floor ~0.05ms) this
# derives ~0.6ms — ROADMAP item 2's target; on this container it
# derives the same software budget in this box's units. The other
# pure-CPU gates (transport residual, batch-64 per-claim, tracing
# slack, p99-under-load) scale the same way but never BELOW their
# committed absolute calibrations (fast boxes keep the old bars).
# Explicit env values always win (explicit > derived).
PERF_FSYNC_FLOOR_MS="${PERF_FSYNC_FLOOR_MS:-$(python "$REPO_ROOT/hack/fsync_probe.py")}"
PERF_CPU_REF_MS="${PERF_CPU_REF_MS:-$(python "$REPO_ROOT/hack/fsync_probe.py" --cpu)}"
PERF_P50_GATE_MS="${PERF_P50_GATE_MS:-$(python -c "
import sys; floor = float(sys.argv[1]); cpu = float(sys.argv[2])
print(round(7.0 * cpu + 2.0 * floor, 3))" "$PERF_FSYNC_FLOOR_MS" "$PERF_CPU_REF_MS")}"
PERF_TRANSPORT_GATE_MS="${PERF_TRANSPORT_GATE_MS:-$(python -c "
import sys; print(round(max(0.35, 1.6 * float(sys.argv[1])), 3))" "$PERF_CPU_REF_MS")}"
PERF_BATCH64_GATE_MS="${PERF_BATCH64_GATE_MS:-$(python -c "
import sys; print(round(max(0.3, 1.4 * float(sys.argv[1])), 3))" "$PERF_CPU_REF_MS")}"
TRACE_OVERHEAD_SLACK_MS="${TRACE_OVERHEAD_SLACK_MS:-$(python -c "
import sys; print(round(max(0.05, 0.5 * float(sys.argv[1])), 3))" "$PERF_CPU_REF_MS")}"
PERF_SUSTAINED_P99_GATE_MS="${PERF_SUSTAINED_P99_GATE_MS:-$(python -c "
import sys; print(round(max(30.0, 120.0 * float(sys.argv[1])), 1))" "$PERF_CPU_REF_MS")}"
echo ">> fdatasync floor ${PERF_FSYNC_FLOOR_MS}ms, cpu ref ${PERF_CPU_REF_MS}ms -> claim-to-ready p50 gate ${PERF_P50_GATE_MS}ms, transport ${PERF_TRANSPORT_GATE_MS}ms, batch64 ${PERF_BATCH64_GATE_MS}ms"

# The sustained throughput/coalescing targets assume a node-class host
# (>= 4 cores), where the 8 framed client connections, the server
# pipeline, and fdatasync scheduling actually run in parallel. On a
# small host (e.g. a single-core CI container) the whole closed loop is
# serialized onto one core, which bounds BOTH the offered load and how
# many co-committers the group-commit window can ever catch in flight
# — no storage-engine change can push a GIL-serialized pipeline past
# ~1ms/RPC. Budget the default gates by core count (same philosophy as
# the fdatasync-floor-relative p50 gate above: gate against this box's
# physics, not an absolute number from a bigger box). Explicit
# PERF_SUSTAINED_RPS_MIN / PERF_COALESCE_RATIO_MIN still win.
PERF_NPROC="$(nproc)"
PERF_SUSTAINED_RPS_MIN="${PERF_SUSTAINED_RPS_MIN:-$(python -c "
import sys; n = int(sys.argv[1])
print(4000 if n >= 4 else 800 * n)" "$PERF_NPROC")}"
PERF_COALESCE_RATIO_MIN="${PERF_COALESCE_RATIO_MIN:-$(python -c "
import sys; n = int(sys.argv[1])
print('4.0' if n >= 4 else '2.5')" "$PERF_NPROC")}"
echo ">> host budget: ${PERF_NPROC} core(s) -> sustained gates >= ${PERF_SUSTAINED_RPS_MIN} RPC/s, coalesce >= ${PERF_COALESCE_RATIO_MIN}"

echo ">> group-commit tripwire (one terminal sync per batch)"
JAX_PLATFORMS=cpu python -m pytest "$REPO_ROOT/tests/test_batch_prepare.py" \
  -q -p no:cacheprovider

echo ">> claim-to-ready probe (${CYCLES} cycles, fake v5p 4-chip + batch-64, framed transport)"
cd "$REPO_ROOT"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  PERF_P50_GATE_MS="$PERF_P50_GATE_MS" \
  PERF_FSYNC_FLOOR_MS="$PERF_FSYNC_FLOOR_MS" \
  PERF_TRANSPORT_GATE_MS="$PERF_TRANSPORT_GATE_MS" \
  PERF_BATCH64_GATE_MS="$PERF_BATCH64_GATE_MS" \
  PERF_CPU_REF_MS="$PERF_CPU_REF_MS" \
  TRACE_OVERHEAD_SLACK_MS="$TRACE_OVERHEAD_SLACK_MS" \
  python - "$CYCLES" <<'EOF'
import json
import os
import statistics
import sys

from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips

import bench

n = int(sys.argv[1])
bd = bench._BenchDriver(FakeBackend(default_fake_chips(4, "v5p")),
                        prefix="tpu-dra-perf-")
try:
    # 15 warm cycles, matching bench_claim_to_ready's documented
    # warmup: the first cycles carry lazy imports, channel
    # establishment and first-touch faults — on a 1-core CI box they
    # smear the gated p50 by several hundred µs.
    for i in range(15):
        bd.cycle(f"warm-{i}")
    wire = {}
    one_dev = [f"chip-{bd.chips[0]}"]
    one_lats = sorted(bd.cycle(f"one-{i}", devices=one_dev, wire=wire)
                      for i in range(n))
    p50_one = statistics.median(one_lats)
    # Transport residual (SURVEY §21): what the wire costs BETWEEN the
    # client clock and the server handler. The framed fast path
    # replaced the sync-gRPC round-trip (~0.5-0.7ms measured here) —
    # gated so the residual cannot silently regrow.
    handler_p50 = statistics.median(sorted(wire["handler"]))
    transport = max(p50_one - handler_p50, 0.0)
    # Old-transport reference (ungated, for the JSON record): the same
    # cycle over the kubelet gRPC socket.
    p50_grpc = bd.config_p50("oneg", max(10, n // 3), devices=one_dev,
                             transport="grpc")
    breakdown = {}
    bd.batch_cycle("bwarm", 4)
    p50_batch = statistics.median(sorted(
        bd.batch_cycle(f"b{i}", 4, breakdown=breakdown)
        for i in range(n)))
    ck = bd.state._ckpt_mgr
    # Tracing-overhead A/B (ISSUE 13): PER-CYCLE alternation — every
    # odd cycle runs tracing-off, every even cycle tracing-on, so both
    # populations share one time window and the 1-core CI box's drift
    # (allocator growth, background ticks) cancels instead of landing
    # on whichever mode ran second. Phase-level medians flapped ±10%
    # run to run; this design measures the systematic span cost
    # (~3-5% here) reproducibly.
    from tpu_dra.infra.trace import TRACER

    trace_off, trace_on = [], []
    tov_dev = [f"chip-{bd.chips[0]}"]
    for i in range(int(os.environ.get("TRACE_OVERHEAD_CYCLES", "80"))):
        TRACER.set_enabled(False)
        try:
            trace_off.append(bd.cycle(f"tovoff{i}", devices=tov_dev))
        finally:
            TRACER.set_enabled(True)
        trace_on.append(bd.cycle(f"tovon{i}", devices=tov_dev))
    trace_off_p50 = statistics.median(trace_off)
    trace_on_p50 = statistics.median(trace_on)

    out = {
        "claim_to_ready_p50_1chip_ms": round(p50_one, 3),
        "claim_to_ready_p50_1chip_grpc_ms": round(p50_grpc, 3),
        "claim_to_ready_transport_residual_ms": round(transport, 3),
        "claim_to_ready_p50_1chip_tracing_off_ms": round(trace_off_p50, 3),
        "claim_to_ready_p50_1chip_tracing_on_ms": round(trace_on_p50, 3),
        "claim_to_ready_p50_batch_per_claim_ms": round(p50_batch, 3),
        "batch_amortization_x": round(p50_one / p50_batch, 2),
        "slot_syncs": ck.slot_syncs,
        "journal_compactions": ck.journal_compactions,
        "journal_window_holds": ck.journal_window_holds,
        "fdatasync_floor_ms": float(os.environ["PERF_FSYNC_FLOOR_MS"]),
        "cpu_ref_ms": float(os.environ["PERF_CPU_REF_MS"]),
    }
    window_holds = ck.journal_window_holds
    for k, vals in sorted(breakdown.items()):
        if k != "n_claims":
            out[f"batch_{k}_ms"] = round(statistics.median(vals), 4)
finally:
    bd.close()

# Batch-64 (ISSUE 7 acceptance: <= 0.2 ms/claim on quiet hardware; the
# gate default carries headroom for CI noise — tune PERF_BATCH64_GATE_MS).
bd64 = bench._BenchDriver(FakeBackend(default_fake_chips(64, "v5p")),
                          prefix="tpu-dra-perf64-")
try:
    bd64.batch_cycle("warm", 64)
    p50_b64 = statistics.median(sorted(
        bd64.batch_cycle(f"b{i}", 64) for i in range(max(10, n // 3))))
    out["claim_to_ready_p50_batch64_per_claim_ms"] = round(p50_b64, 4)
finally:
    bd64.close()
print(json.dumps(out))

if p50_batch >= p50_one:
    sys.exit("REGRESSION: batched per-claim p50 not below single-claim p50")
gate = float(os.environ["PERF_P50_GATE_MS"])
if p50_one > gate:
    sys.exit(f"REGRESSION: claim_to_ready_p50_1chip_ms {p50_one:.3f} > "
             f"{gate} (PERF_P50_GATE_MS, derived from the "
             f"{os.environ['PERF_FSYNC_FLOOR_MS']}ms fdatasync floor)")
# ISSUE 17 tripwire: this whole probe is SEQUENTIAL — one client, one
# RPC in flight — so the adaptive group-commit window must never have
# held. A nonzero count means idle commits are paying window latency,
# exactly the failure mode the arrival-rate + co-committer-evidence
# predicate exists to prevent.
if window_holds:
    sys.exit(f"REGRESSION: group-commit window held {window_holds} "
             "time(s) under a strictly sequential load — the adaptive "
             "window is taxing idle commits")
tgate = float(os.environ["PERF_TRANSPORT_GATE_MS"])
if transport > tgate:
    sys.exit(f"REGRESSION: transport residual {transport:.3f}ms > {tgate} "
             "(PERF_TRANSPORT_GATE_MS) — the framed fast path's wire "
             "share regrew toward the sync-gRPC floor the ISSUE 15 "
             "swap removed")
gate64 = float(os.environ["PERF_BATCH64_GATE_MS"])
if p50_b64 > gate64:
    sys.exit(f"REGRESSION: claim_to_ready_p50_batch64_per_claim_ms "
             f"{p50_b64:.4f} > {gate64} (PERF_BATCH64_GATE_MS)")
# ISSUE 13 gate: enabling tracing moves claim-to-ready by <=5% (plus a
# small absolute slack absorbing sub-0.1ms scheduler jitter on ~1ms
# medians; tune TRACE_OVERHEAD_PCT / TRACE_OVERHEAD_SLACK_MS).
pct = float(os.environ.get("TRACE_OVERHEAD_PCT", "5"))
slack = float(os.environ.get("TRACE_OVERHEAD_SLACK_MS", "0.05"))
if trace_on_p50 > trace_off_p50 * (1 + pct / 100.0) + slack:
    sys.exit(f"REGRESSION: tracing-on claim-to-ready p50 "
             f"{trace_on_p50:.3f}ms exceeds tracing-off "
             f"{trace_off_p50:.3f}ms by more than {pct}% "
             f"(+{slack}ms slack) — the span layer grew a hot-path cost")
EOF

echo ">> sustained-load gates (${PERF_SUSTAINED_S:-25}s mixed-batch prepare/unprepare at production RPS)"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  PERF_SUSTAINED_S="${PERF_SUSTAINED_S:-25}" \
  PERF_SUSTAINED_RPS_MIN="$PERF_SUSTAINED_RPS_MIN" \
  PERF_SUSTAINED_P99_GATE_MS="$PERF_SUSTAINED_P99_GATE_MS" \
  PERF_COALESCE_RATIO_MIN="$PERF_COALESCE_RATIO_MIN" \
  python - <<'EOF'
import json
import os
import sys

import bench

out = bench.bench_prepare_sustained(
    duration_s=float(os.environ["PERF_SUSTAINED_S"]))
print(json.dumps(out))
if out["prepare_sustained_errors"]:
    sys.exit(f"REGRESSION: {out['prepare_sustained_errors']} RPC errors "
             f"under sustained load (first: "
             f"{out.get('prepare_sustained_first_error')})")
if out["prepare_sustained_leaked_claims"]:
    sys.exit(f"REGRESSION: {out['prepare_sustained_leaked_claims']} claims "
             "still prepared after the sustained churn drained")
rps_min = float(os.environ["PERF_SUSTAINED_RPS_MIN"])
if out["prepare_sustained_rpcs_per_s"] < rps_min:
    sys.exit(f"REGRESSION: sustained rate "
             f"{out['prepare_sustained_rpcs_per_s']} RPC/s < {rps_min} "
             "(PERF_SUSTAINED_RPS_MIN) — one node no longer holds "
             "production claim-churn RPS")
p99_gate = float(os.environ["PERF_SUSTAINED_P99_GATE_MS"])
if out["prepare_sustained_single_p99_ms"] > p99_gate:
    sys.exit(f"REGRESSION: single-claim p99 under load "
             f"{out['prepare_sustained_single_p99_ms']}ms > {p99_gate} "
             "(PERF_SUSTAINED_P99_GATE_MS)")
# In-flight-window behavior: the admission window (16) must bound what
# gets past admission no matter the offered load.
if out["prepare_sustained_pipeline_inflight_peak"] > 16:
    sys.exit(f"REGRESSION: pipeline in-flight peak "
             f"{out['prepare_sustained_pipeline_inflight_peak']} exceeds "
             "the admission window (16)")
# Sync-coalescing AT DEPTH (ISSUE 15, replacing the old idle-probe's
# 5-round opportunistic retry loop): with >= 8 RPCs in flight for a
# meaningful fraction of the run the barrier queue is provably full,
# so the ratio is deterministic — no retries.
if out["prepare_sustained_depth8_pct"] < 20.0:
    sys.exit(f"REGRESSION: sustained load only reached depth >= 8 for "
             f"{out['prepare_sustained_depth8_pct']}% of samples — the "
             "coalescing-at-depth gate has no depth to measure")
ratio_min = float(os.environ["PERF_COALESCE_RATIO_MIN"])
ratio = out["prepare_sustained_coalesce_ratio"]
if ratio is None or ratio < ratio_min:
    sys.exit(f"REGRESSION: journal coalesce ratio {ratio} < {ratio_min} "
             f"(appends={out['prepare_sustained_journal_appends']}, "
             f"group_syncs={out['prepare_sustained_journal_group_syncs']})"
             " — the cross-RPC group commit stopped sharing fdatasyncs "
             "at depth")
EOF

echo ">> hot-restart phase (${PERF_RESTART_S:-12}s churn across ${PERF_RESTARTS:-2} plugin restarts: zero failed RPCs)"
# ISSUE 16 gates (SURVEY §22): restart the kubelet plugin mid-stream
# under sustained prepare/unprepare churn. The drain window bounds the
# in-flight quiesce, the checkpoint journal + idempotent prepare
# recover the claim set, and the framed clients' bounded
# retry-on-reconnect masks the socket gap — so the gate is literal:
# ZERO failed RPCs, zero leaked claims, drain window under
# PERF_DRAIN_GATE_S (default 5; measures ~0.005 — the gate carries
# headroom for CI boxes where an in-flight batch straddles the drain).
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  TPU_DRA_BENCH_RESTART_S="${PERF_RESTART_S:-12}" \
  TPU_DRA_BENCH_RESTARTS="${PERF_RESTARTS:-2}" \
  PERF_DRAIN_GATE_S="${PERF_DRAIN_GATE_S:-5}" \
  python - <<'EOF'
import json
import os
import sys

import bench

out = bench.bench_hot_restart()
print(json.dumps(out))
if out.get("hot_restart_error"):
    sys.exit(f"REGRESSION: hot-restart phase error: "
             f"{out['hot_restart_error']}")
if out["hot_restart_failed_rpcs"]:
    sys.exit(f"REGRESSION: {out['hot_restart_failed_rpcs']} failed RPCs "
             f"across {out['hot_restart_restarts']} plugin restarts "
             f"(first: {out.get('hot_restart_first_error')}) — the "
             "drain + retry-on-reconnect contract must mask the "
             "restart gap completely")
if out["hot_restart_leaked_claims"]:
    sys.exit(f"REGRESSION: {out['hot_restart_leaked_claims']} claims "
             "leaked across the restarts (journal recovery lost state)")
drain_gate = float(os.environ["PERF_DRAIN_GATE_S"])
if out["hot_restart_drain_s_max"] > drain_gate:
    sys.exit(f"REGRESSION: drain window "
             f"{out['hot_restart_drain_s_max']}s > {drain_gate}s "
             "(PERF_DRAIN_GATE_S) — shutdown no longer quiesces the "
             "admission pipeline promptly")
if out["hot_restart_reconnects"] < out["hot_restart_restarts"]:
    sys.exit(f"REGRESSION: only {out['hot_restart_reconnects']} client "
             f"reconnects across {out['hot_restart_restarts']} restarts "
             "— the phase did not actually exercise the reconnect path")
EOF

echo ">> scheduler failover phase (HA lease takeover to first allocation under churn)"
# ISSUE 16 gate: active-standby takeover latency. The floor is the
# lease expiry wait itself (0.4s lease duration in the bench), so the
# p50 gate (default 2000ms) is a tripwire against takeover-resync
# pathology (full resync thrash, fencing livelock), not a latency SLO.
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  TPU_DRA_BENCH_FAILOVER_N="${PERF_FAILOVER_N:-5}" \
  PERF_FAILOVER_P50_GATE_MS="${PERF_FAILOVER_P50_GATE_MS:-2000}" \
  python - <<'EOF'
import json
import os
import sys

import bench

out = bench.bench_sched_failover()
print(json.dumps(out))
if out.get("sched_failover_error"):
    sys.exit(f"REGRESSION: failover phase error: "
             f"{out['sched_failover_error']}")
gate = float(os.environ["PERF_FAILOVER_P50_GATE_MS"])
if out["sched_failover_to_alloc_p50_ms"] > gate:
    sys.exit(f"REGRESSION: failover-to-first-allocation p50 "
             f"{out['sched_failover_to_alloc_p50_ms']}ms > {gate}ms "
             "(PERF_FAILOVER_P50_GATE_MS) — standby takeover stopped "
             "resuming allocation promptly after lease expiry")
EOF

echo ">> CEL compile-cache tripwire tests"
JAX_PLATFORMS=cpu python -m pytest "$REPO_ROOT/tests/test_cel_cache.py" \
  -q -p no:cacheprovider

echo ">> scheduler churn gates (${SCHED_NODES:-100} nodes x ${SCHED_PODS:-500} pods, fake backend)"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  SCHED_NODES="${SCHED_NODES:-100}" SCHED_PODS="${SCHED_PODS:-500}" \
  python - <<'EOF'
import glob
import json
import os
import re
import sys

import bench
from tpu_dra.infra.trace import TRACER

# Tracing-overhead A/B at churn scale (ISSUE 13): paired off/on passes
# with the WITHIN-PAIR ORDER alternating each round — the 1-core CI
# box's throughput drifts over a session, so a fixed order would
# silently credit whichever mode always ran first. The gate is the
# MEDIAN of the per-pair on/off ratios (drift cancels within a pair,
# the median shrugs off one outlier pair). The gated churn numbers
# below come from the best tracing-ON pass (tracing is the production
# default).
import statistics

nodes, pods = int(os.environ["SCHED_NODES"]), int(os.environ["SCHED_PODS"])


def churn_pass(tracing_on):
    TRACER.set_enabled(tracing_on)
    try:
        return bench.bench_sched_churn(n_nodes=nodes, n_pods=pods)
    finally:
        TRACER.set_enabled(True)


churn_on, ratios = [], []
for r in range(int(os.environ.get("TRACE_OVERHEAD_CHURN_ROUNDS", "4"))):
    first_on = r % 2 == 1
    a = churn_pass(tracing_on=first_on)
    b = churn_pass(tracing_on=not first_on)
    on_r, off_r = (a, b) if first_on else (b, a)
    churn_on.append(on_r)
    ratios.append(on_r["sched_throughput_pods_per_s"]
                  / max(off_r["sched_throughput_pods_per_s"], 1e-9))
out = max(churn_on, key=lambda r: r["sched_throughput_pods_per_s"])
out["sched_throughput_tracing_ratio"] = round(
    statistics.median(ratios), 3)
print(json.dumps(out))
pct = float(os.environ.get("TRACE_OVERHEAD_PCT", "5"))
if statistics.median(ratios) < 1 - pct / 100.0:
    sys.exit(f"REGRESSION: tracing-on sched throughput is "
             f"{(1 - statistics.median(ratios)) * 100:.1f}% below "
             f"tracing-off (median of {len(ratios)} order-alternated "
             f"pairs; gate {pct}%) — the span layer grew a scheduler "
             "hot-path cost")
if out["sched_full_relists"] != 0:
    sys.exit(f"REGRESSION: {out['sched_full_relists']} steady-state full "
             "relists (event-driven scheduler must not poll-and-scan)")
if out["sched_cel_compiles"] > out["sched_cel_distinct_exprs"]:
    sys.exit("REGRESSION: CEL compiles "
             f"({out['sched_cel_compiles']}) exceed distinct expressions "
             f"({out['sched_cel_distinct_exprs']}) — compile cache broken")
if out.get("sched_churn_gc_leak"):
    sys.exit(f"REGRESSION: {out['sched_churn_gc_leak']} claims leaked "
             "after pod deletion (event-driven GC broken)")

# p50 tripwire vs the newest BENCH round that recorded the metric
# (pre-ISSUE-3 rounds did not; the first recording round sets the bar).
prev = None
for path in sorted(glob.glob("BENCH_r*.json"),
                   key=lambda p: int(re.search(r"r(\d+)", p).group(1)),
                   reverse=True):
    with open(path) as f:
        doc = json.load(f)
    # ISSUE 17: rounds now record parsed metrics under a structured
    # "metrics" key (older rounds buried them in the tail blob).
    v = (doc.get("sched_pod_to_allocated_p50_ms")
         or doc.get("metrics", {}).get("sched_pod_to_allocated_p50_ms"))
    if v is not None:
        prev = (path, v)
        break
if prev is not None and out["sched_pod_to_allocated_p50_ms"] > prev[1] * 1.5:
    sys.exit(f"REGRESSION: sched_pod_to_allocated_p50_ms "
             f"{out['sched_pod_to_allocated_p50_ms']} > 1.5x {prev[1]} "
             f"({prev[0]})")
EOF

echo ">> scaled scheduler churn gates (${SCHED_SCALED_NODES:-1000} nodes x ${SCHED_SCALED_PODS:-5000} pods, vs r05 single-worker baseline)"
# Baseline: the r05 scheduler (commit 2137df2, single worker) measured
# at 1000x5000 on THIS container (2026-08-03, git worktree at HEAD):
# 313.1 pods/s, p50 191.0ms, p95 288.2ms. Re-measure and override via
# env when gating in a different environment.
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  SCHED_SCALED_NODES="${SCHED_SCALED_NODES:-1000}" \
  SCHED_SCALED_PODS="${SCHED_SCALED_PODS:-5000}" \
  SCHED_SCALED_BASELINE_PPS="${SCHED_SCALED_BASELINE_PPS:-313.1}" \
  SCHED_SCALED_BASELINE_P50_MS="${SCHED_SCALED_BASELINE_P50_MS:-191.0}" \
  python - <<'EOF'
import json
import os
import sys

import bench

nodes = int(os.environ["SCHED_SCALED_NODES"])
pods = int(os.environ["SCHED_SCALED_PODS"])
base_pps = float(os.environ["SCHED_SCALED_BASELINE_PPS"])
base_p50 = float(os.environ["SCHED_SCALED_BASELINE_P50_MS"])

w1 = bench.bench_sched_churn(n_nodes=nodes, n_pods=pods, workers=1)
print(json.dumps({f"w1_{k}": v for k, v in w1.items()
                  if k.startswith("sched_")}))
if w1["sched_full_relists"] != 0:
    sys.exit(f"REGRESSION: {w1['sched_full_relists']} full relists in the "
             "scaled single-worker churn")
if w1["sched_throughput_pods_per_s"] < 2.0 * base_pps:
    sys.exit(f"REGRESSION: scaled single-worker throughput "
             f"{w1['sched_throughput_pods_per_s']} pods/s < 2x r05 "
             f"baseline {base_pps} (ISSUE 8 gate)")
if w1["sched_pod_to_allocated_p50_ms"] > 2.0 * base_p50:
    sys.exit(f"REGRESSION: scaled single-worker p50 "
             f"{w1['sched_pod_to_allocated_p50_ms']}ms > 2x r05 baseline "
             f"{base_p50}ms (ISSUE 8 gate)")

pool = bench.bench_sched_churn(n_nodes=nodes, n_pods=pods)  # default pool
print(json.dumps({f"pool_{k}": v for k, v in pool.items()
                  if k.startswith("sched_")}))
if pool["sched_full_relists"] != 0:
    sys.exit(f"REGRESSION: {pool['sched_full_relists']} full relists in "
             "the scaled pool churn")
if pool["sched_workers"] < 2:
    sys.exit("REGRESSION: the scaled pool pass ran single-worker — the "
             "multi-worker default was lost")
if pool["sched_throughput_pods_per_s"] < base_pps:
    sys.exit(f"REGRESSION: scaled pool throughput "
             f"{pool['sched_throughput_pods_per_s']} pods/s regressed "
             f"below the r05 single-worker baseline {base_pps} — the "
             "worker pool must never cost more than it buys")
EOF

echo ">> scale-out churn gates (${PERF_SCALE10K_NODES:-10000} nodes x ${PERF_SCALE10K_PODS:-100000} pods, kubemark-style hollow fleet)"
# 5. 10k-node scale-out gates (ISSUE 18, SURVEY §24): the kubemark-
#    style bench — 100k pod lifecycles through the real scheduler pool
#    on a 10k-node inventory, with PERF_SCALE10K_WATCHERS hollow-node
#    field-selector watchers riding the sharded watch fan-out. Gates:
#    - throughput within 2x of the SAME-RUN 1000-node baseline
#      (ratio >= PERF_SCALE10K_RATIO, default 0.5): scaling nodes 10x
#      may cost at most half the cluster-wide rate;
#    - an absolute host-budgeted floor (>= PERF_SCALE10K_MIN_PPS,
#      default derived from the cpu ref: ~25/cpu_ref pods/s, i.e.
#      ~130 pods/s on a desktop-class core) so BOTH runs collapsing
#      together cannot go green on ratio alone;
#    - zero scheduler full relists at 10k nodes (event-driven, never
#      poll-and-scan) and zero snapshot-isolation conflicts repaired
#      by luck — plus zero hollow-watcher queue overflows (the fan-out
#      must keep per-watcher delivery at scoped volume);
#    - hollow isolation: the busiest scoped watcher must see < 20% of
#      the cluster-wide pod event volume (under the old broadcast
#      fan-out every watcher decoded 100% of it).
#    Sizes override via PERF_SCALE10K_NODES/PODS/WATCHERS for smaller
#    CI boxes; BENCH recording rounds run the defaults.
PERF_SCALE10K_MIN_PPS="${PERF_SCALE10K_MIN_PPS:-$(python -c "
import sys; print(round(min(400.0, 25.0 / float(sys.argv[1])), 1))" "$PERF_CPU_REF_MS")}"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  TPU_DRA_BENCH_SCALE10K_NODES="${PERF_SCALE10K_NODES:-10000}" \
  TPU_DRA_BENCH_SCALE10K_PODS="${PERF_SCALE10K_PODS:-100000}" \
  TPU_DRA_BENCH_SCALE10K_WATCHERS="${PERF_SCALE10K_WATCHERS:-100}" \
  PERF_SCALE10K_RATIO="${PERF_SCALE10K_RATIO:-0.5}" \
  PERF_SCALE10K_MIN_PPS="$PERF_SCALE10K_MIN_PPS" \
  python - <<'EOF'
import json
import os
import sys

import bench

out = bench.bench_sched_scale10k()
print(json.dumps(out))
ratio_floor = float(os.environ["PERF_SCALE10K_RATIO"])
pps_floor = float(os.environ["PERF_SCALE10K_MIN_PPS"])
pps = out["sched_scale10k_throughput_pods_per_s"]
ratio = out["sched_scale10k_throughput_ratio"]
if out["sched_scale10k_full_relists"] != 0:
    sys.exit(f"REGRESSION: {out['sched_scale10k_full_relists']} full "
             "relists in the 10k-node churn — the scale-out fan-out "
             "fell back to poll-and-scan")
if ratio is None or ratio < ratio_floor:
    sys.exit(f"REGRESSION: 10k-node throughput {pps} pods/s is "
             f"{ratio}x the same-run 1000-node baseline "
             f"{out['sched_scale10k_baseline_throughput_pods_per_s']} "
             f"(< {ratio_floor}x — ISSUE 18 gate: within 2x)")
if pps < pps_floor:
    sys.exit(f"REGRESSION: 10k-node throughput {pps} pods/s under the "
             f"host-budgeted floor {pps_floor} (cpu-ref-derived)")
if out["sched_scale10k_hollow_overflow_errors"] != 0:
    sys.exit(f"REGRESSION: "
             f"{out['sched_scale10k_hollow_overflow_errors']} hollow "
             "watchers hit queue-overflow 410 — scoped delivery volume "
             "exceeded the per-watcher bound")
total_pod_events = 2 * out["sched_scale10k_churn_pods"]  # bind + delete
hot = out["sched_scale10k_hollow_events_max"]
if hot >= 0.2 * total_pod_events:
    sys.exit(f"REGRESSION: busiest scoped watcher saw {hot} events "
             f"(>= 20% of {total_pod_events} cluster-wide) — the "
             "field-selector index degraded toward broadcast fan-out")
EOF

echo ">> data-plane gates (topology-allocated mesh psum + placement A/B)"
# ISSUE 10 gates: the psum must run on EVERY chip the driver allocated
# on the fake multi-host backend (coverage N/N with psum_devices > 1,
# nonzero bandwidth), every workload must attribute a number on the
# allocated mesh, and the placement-quality A/B must show the delta the
# topology scorer claims: contiguous >= fragmented on modeled ICI
# bandwidth — STRICTLY greater when the modeled topologies differ —
# and byte-identical across runs (hop-count model, no randomness).
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake python - <<'EOF'
import json
import sys

import bench

out = bench.bench_mesh_dataplane()
print(json.dumps(out))
for err_key in ("psum_mesh_error", "psum_mesh_psum_error", "psum_ab_error"):
    if out.get(err_key):
        sys.exit(f"REGRESSION: data-plane phase error: "
                 f"{err_key}={out[err_key]}")
if out.get("psum_mesh_devices", 0) <= 1:
    sys.exit(f"REGRESSION: psum ran on {out.get('psum_mesh_devices')} "
             "devices — the multi-process mesh wiring degraded to "
             "single-device (the r01-r05 gap ISSUE 10 closes)")
used, allocated = out["psum_mesh_coverage"].split("/")
if used != allocated:
    sys.exit(f"REGRESSION: psum coverage {out['psum_mesh_coverage']} — "
             "the collective did not cover every allocated chip")
if not out.get("psum_mesh_algo_gbps", 0) > 0:
    sys.exit("REGRESSION: psum on the allocated mesh reports no "
             f"bandwidth ({out.get('psum_mesh_algo_gbps')})")
# The authoritative workload list is the meshbuild registry itself — a
# newly registered workload is gated here automatically.
from tpu_dra.workloads.meshbuild import WORKLOADS

for wl in list(WORKLOADS)[1:]:
    if out.get(f"mesh_workload_{wl}_error"):
        sys.exit(f"REGRESSION: workload {wl} failed on the allocated "
                 f"mesh: {out[f'mesh_workload_{wl}_error']}")
    if not any(k.startswith(f"mesh_workload_{wl}_") for k in out):
        sys.exit(f"REGRESSION: workload {wl} reported nothing on the "
                 "allocated mesh")
contig = out["psum_ab_contiguous_gbps"]
frag = out["psum_ab_fragmented_gbps"]
if contig < frag:
    sys.exit(f"REGRESSION: contiguous allocation models {contig} GB/s "
             f"< fragmented {frag} — the topology scorer's contiguity "
             "preference buys nothing")
if (out["psum_ab_contiguous_hop_mean"] != out["psum_ab_fragmented_hop_mean"]
        and not contig > frag):
    sys.exit(f"REGRESSION: modeled topologies differ (hop means "
             f"{out['psum_ab_contiguous_hop_mean']} vs "
             f"{out['psum_ab_fragmented_hop_mean']}) but contiguous "
             f"{contig} is not strictly above fragmented {frag}")

# Determinism: the gated A/B numbers are pure functions of the two
# coordinate sets — two fresh modeled-only rounds must agree exactly.
a = bench._ab_placement_section(measure=False)
b = bench._ab_placement_section(measure=False)
if "psum_ab_error" in a or a != b:
    sys.exit(f"REGRESSION: modeled A/B is not deterministic across "
             f"runs:\n{a}\n{b}")
EOF

echo ">> topology gates (4x4x4 torus churn, TopologyAwareScheduling on)"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake python - <<'EOF'
import glob
import json
import re
import sys

import bench

out = bench.bench_topology()
print(json.dumps(out))
if out["topo_contiguity_ratio"] != 1.0:
    sys.exit(f"REGRESSION: topo_contiguity_ratio "
             f"{out['topo_contiguity_ratio']} != 1.0 — multi-chip picks "
             "degraded to first-fit on a coordinate-publishing inventory")
if out["topo_unplaced_pods"]:
    sys.exit(f"REGRESSION: {out['topo_unplaced_pods']} pods never placed "
             "— fragmentation scoring stopped preserving free cuboids")

# p50 tripwire vs the newest BENCH round that recorded the metric
# (pre-ISSUE-4 rounds did not; the first recording round sets the bar).
prev = None
for path in sorted(glob.glob("BENCH_r*.json"),
                   key=lambda p: int(re.search(r"r(\d+)", p).group(1)),
                   reverse=True):
    with open(path) as f:
        doc = json.load(f)
    # ISSUE 17: see the sched tripwire — metrics may sit under the
    # structured "metrics" key in newer rounds.
    v = (doc.get("topo_place_p50_ms")
         or doc.get("metrics", {}).get("topo_place_p50_ms"))
    if v is not None:
        prev = (path, v)
        break
if prev is not None and out["topo_place_p50_ms"] > prev[1] * 1.5:
    sys.exit(f"REGRESSION: topo_place_p50_ms "
             f"{out['topo_place_p50_ms']} > 1.5x {prev[1]} ({prev[0]})")
EOF
echo ">> perf tier green"
