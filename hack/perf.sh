#!/usr/bin/env bash
# Perf tier: the claim-to-ready hot path's regression tripwires (ISSUE 2)
# plus the event-driven control plane's gates (ISSUE 3):
#
#   hack/perf.sh [CYCLES]
#
# 1. The group-commit tripwire tests (tests/test_batch_prepare.py): a
#    batched prepare/unprepare of N claims must land exactly ONE
#    terminal checkpoint store / device sync (asserted against the
#    CheckpointManager store counters) — N syncs means the group commit
#    silently degraded back to per-claim commits.
# 2. A quick claim-to-ready probe through the real gRPC path (single
#    claim p50 + batched per-claim p50 on a fake 4-chip v5p inventory),
#    printed as one JSON line for eyeballing against BENCH_r*.json.
# 3. Scheduler churn gates on the fake backend (SCHED_NODES x
#    SCHED_PODS, defaults 100x500): steady-state full relists MUST be 0
#    (event-driven, not poll-and-scan), CEL compiles MUST not exceed
#    distinct selector sources (compile cache), claim GC must drain, and
#    the pod-to-allocated p50 must not regress >50% against the newest
#    BENCH_r*.json round that recorded it.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
CYCLES="${1:-${PERF_CYCLES:-30}}"

echo ">> group-commit tripwire (one terminal sync per batch)"
JAX_PLATFORMS=cpu python -m pytest "$REPO_ROOT/tests/test_batch_prepare.py" \
  -q -p no:cacheprovider

echo ">> claim-to-ready probe (${CYCLES} cycles, fake v5p 4-chip)"
cd "$REPO_ROOT"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake python - "$CYCLES" <<'EOF'
import json
import statistics
import sys

from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips

import bench

n = int(sys.argv[1])
bd = bench._BenchDriver(FakeBackend(default_fake_chips(4, "v5p")),
                        prefix="tpu-dra-perf-")
try:
    for i in range(5):
        bd.cycle(f"warm-{i}")
    p50_one = bd.config_p50("one", n, devices=[f"chip-{bd.chips[0]}"])
    breakdown = {}
    bd.batch_cycle("bwarm", 4)
    p50_batch = statistics.median(sorted(
        bd.batch_cycle(f"b{i}", 4, breakdown=breakdown)
        for i in range(n)))
    out = {
        "claim_to_ready_p50_1chip_ms": round(p50_one, 3),
        "claim_to_ready_p50_batch_per_claim_ms": round(p50_batch, 3),
        "batch_amortization_x": round(p50_one / p50_batch, 2),
        "terminal_stores": bd.state._ckpt_mgr.terminal_stores,
        "slot_syncs": bd.state._ckpt_mgr.slot_syncs,
    }
    for k, vals in sorted(breakdown.items()):
        if k != "n_claims":
            out[f"batch_{k}_ms"] = round(statistics.median(vals), 4)
finally:
    bd.close()
print(json.dumps(out))
if p50_batch >= p50_one:
    sys.exit("REGRESSION: batched per-claim p50 not below single-claim p50")
EOF

echo ">> CEL compile-cache tripwire tests"
JAX_PLATFORMS=cpu python -m pytest "$REPO_ROOT/tests/test_cel_cache.py" \
  -q -p no:cacheprovider

echo ">> scheduler churn gates (${SCHED_NODES:-100} nodes x ${SCHED_PODS:-500} pods, fake backend)"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
  SCHED_NODES="${SCHED_NODES:-100}" SCHED_PODS="${SCHED_PODS:-500}" \
  python - <<'EOF'
import glob
import json
import os
import re
import sys

import bench

out = bench.bench_sched_churn(n_nodes=int(os.environ["SCHED_NODES"]),
                              n_pods=int(os.environ["SCHED_PODS"]))
print(json.dumps(out))
if out["sched_full_relists"] != 0:
    sys.exit(f"REGRESSION: {out['sched_full_relists']} steady-state full "
             "relists (event-driven scheduler must not poll-and-scan)")
if out["sched_cel_compiles"] > out["sched_cel_distinct_exprs"]:
    sys.exit("REGRESSION: CEL compiles "
             f"({out['sched_cel_compiles']}) exceed distinct expressions "
             f"({out['sched_cel_distinct_exprs']}) — compile cache broken")
if out.get("sched_churn_gc_leak"):
    sys.exit(f"REGRESSION: {out['sched_churn_gc_leak']} claims leaked "
             "after pod deletion (event-driven GC broken)")

# p50 tripwire vs the newest BENCH round that recorded the metric
# (pre-ISSUE-3 rounds did not; the first recording round sets the bar).
prev = None
for path in sorted(glob.glob("BENCH_r*.json"),
                   key=lambda p: int(re.search(r"r(\d+)", p).group(1)),
                   reverse=True):
    with open(path) as f:
        doc = json.load(f)
    if doc.get("sched_pod_to_allocated_p50_ms") is not None:
        prev = (path, doc["sched_pod_to_allocated_p50_ms"])
        break
if prev is not None and out["sched_pod_to_allocated_p50_ms"] > prev[1] * 1.5:
    sys.exit(f"REGRESSION: sched_pod_to_allocated_p50_ms "
             f"{out['sched_pod_to_allocated_p50_ms']} > 1.5x {prev[1]} "
             f"({prev[0]})")
EOF

echo ">> topology gates (4x4x4 torus churn, TopologyAwareScheduling on)"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake python - <<'EOF'
import glob
import json
import re
import sys

import bench

out = bench.bench_topology()
print(json.dumps(out))
if out["topo_contiguity_ratio"] != 1.0:
    sys.exit(f"REGRESSION: topo_contiguity_ratio "
             f"{out['topo_contiguity_ratio']} != 1.0 — multi-chip picks "
             "degraded to first-fit on a coordinate-publishing inventory")
if out["topo_unplaced_pods"]:
    sys.exit(f"REGRESSION: {out['topo_unplaced_pods']} pods never placed "
             "— fragmentation scoring stopped preserving free cuboids")

# p50 tripwire vs the newest BENCH round that recorded the metric
# (pre-ISSUE-4 rounds did not; the first recording round sets the bar).
prev = None
for path in sorted(glob.glob("BENCH_r*.json"),
                   key=lambda p: int(re.search(r"r(\d+)", p).group(1)),
                   reverse=True):
    with open(path) as f:
        doc = json.load(f)
    if doc.get("topo_place_p50_ms") is not None:
        prev = (path, doc["topo_place_p50_ms"])
        break
if prev is not None and out["topo_place_p50_ms"] > prev[1] * 1.5:
    sys.exit(f"REGRESSION: topo_place_p50_ms "
             f"{out['topo_place_p50_ms']} > 1.5x {prev[1]} ({prev[0]})")
EOF
echo ">> perf tier green"
