#!/usr/bin/env python3
"""Drive the TSan builds of the native daemons with hostile concurrency.

tpu-multiprocess-coordinator: N threads hammer register/release/query over
its unix socket while probes run; any TSan report makes the binary exit 66
(TSAN_OPTIONS halt_on_error=1 exitcode=66 set by hack/race.sh).

tpu-slice-daemon: concurrent --check probes plus an idle client against
the serve loop.
"""
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

COORD = os.environ["TSAN_COORD"]
DAEMON = os.environ["TSAN_DAEMON"]
THREADS = 8
SECONDS = 5.0


def hammer_coordinator(sock_dir: str, stop: threading.Event) -> None:
    while not stop.is_set():
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(2)
            s.connect(os.path.join(sock_dir, "coordinator.sock"))
            s.sendall(b"Q\n")
            s.recv(128)
            s.sendall(f"R {os.getpid()}\n".encode())
            reply = s.recv(128).decode()
            if reply.startswith("OK"):
                lease = reply.split()[1]
                s.sendall(f"U {lease}\n".encode())
                s.recv(128)
            s.sendall(b"L\n")
            s.recv(256)
            s.close()
        except OSError:
            time.sleep(0.01)


def main() -> int:
    rc = 0
    with tempfile.TemporaryDirectory(dir="/tmp") as tmp:
        d = os.path.join(tmp, "c")
        proc = subprocess.Popen(
            [COORD, "--dir", d, "--chips", "0", "--max-clients", "4"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        deadline = time.time() + 10
        sock = os.path.join(d, "pipe", "coordinator.sock")
        while time.time() < deadline and not os.path.exists(sock):
            time.sleep(0.05)
        stop = threading.Event()
        threads = [threading.Thread(target=hammer_coordinator,
                                    args=(os.path.join(d, "pipe"), stop),
                                    daemon=True) for _ in range(THREADS)]
        for t in threads:
            t.start()
        # Idle client while hammering (serve-loop robustness under TSan).
        idle = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        idle.connect(sock)
        # Vacuous-pass guard: the drive must actually observe READY
        # responses — a daemon that failed to start would otherwise make
        # every probe fail with a non-66 code the old logic ignored.
        ready_seen = 0
        for _ in range(int(SECONDS / 0.5)):
            check = subprocess.run([COORD, "--check", "--dir", d],
                                   capture_output=True, timeout=15)
            if check.returncode == 66:
                print("TSan report in coordinator --check", file=sys.stderr)
                rc = 1
            elif check.returncode == 0:
                ready_seen += 1
            time.sleep(0.5)
        if ready_seen == 0:
            print("coordinator never answered READY — no race coverage",
                  file=sys.stderr)
            rc = 1
        stop.set()
        for t in threads:
            t.join(timeout=2)
        idle.close()
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        if proc.returncode == 66:
            print("TSan report in coordinator:", file=sys.stderr)
            print((proc.stderr.read() or b"").decode()[-3000:],
                  file=sys.stderr)
            rc = 1

        # slice daemon: serve + concurrent checks + idle client
        port = _free_port()
        cfg = os.path.join(tmp, "daemon.cfg")
        nodes_cfg = os.path.join(tmp, "nodes.cfg")
        open(nodes_cfg, "w").close()
        with open(cfg, "w") as f:
            f.write(f"node_ip=127.0.0.1\nport={port}\n"
                    f"nodes_config={nodes_cfg}\nslice_id=s0\n"
                    f"worker_index=0\n")
        dproc = subprocess.Popen(
            [DAEMON, "--config", cfg],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        time.sleep(0.5)
        idle2 = socket.socket()
        try:
            idle2.connect(("127.0.0.1", port))
        except OSError:
            pass
        checks = []
        for _ in range(10):
            checks.append(subprocess.Popen(
                [DAEMON, "--check", "--port", str(port)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        daemon_ready = 0
        for c in checks:
            c.wait(timeout=15)
            if c.returncode == 66:
                print("TSan report in slice-daemon --check", file=sys.stderr)
                rc = 1
            elif c.returncode == 0:
                daemon_ready += 1
        if daemon_ready == 0:
            print("slice-daemon never answered READY — no race coverage",
                  file=sys.stderr)
            rc = 1
        idle2.close()
        dproc.terminate()
        try:
            dproc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            dproc.kill()
            dproc.wait()
        if dproc.returncode == 66:
            print("TSan report in slice-daemon:", file=sys.stderr)
            print((dproc.stderr.read() or b"").decode()[-3000:],
                  file=sys.stderr)
            rc = 1
    print("tsan drive:", "FAIL" if rc else "clean")
    return rc


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


if __name__ == "__main__":
    sys.exit(main())
