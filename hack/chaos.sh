#!/usr/bin/env bash
# Chaos tier: seeded randomized fault schedules driven to quiesce, with
# convergence invariants asserted after every schedule (ISSUE 1):
#
#   hack/chaos.sh [SEEDS] [EVENTS]
#
# 1. The fixed seed matrix (default seeds 0..24, 60 lifecycle events
#    each) through tpu_dra.simcluster.chaos — claim convergence, no
#    orphaned CDI specs, no leaked checkpoints, ResourceSlice vs
#    healthy-chip consistency — plus the dropped-watch + API-flake
#    informer recovery scenario, the scheduler-churn walk (workers=4:
#    the multi-worker pool, sharded index and optimistic snapshot
#    commits run under every schedule, incl. the sched.shard_apply /
#    sched.snapshot_commit fault sites), the topology walk
#    (TopologyAwareScheduling on: every multi-chip allocation an
#    ICI-contiguous cuboid, topology free-set == the allocation index
#    after quiesce), and the node-death walk (SURVEY §18: node loss +
#    chip quarantine racing pod churn with sched.evict armed — every
#    evicted claim ends Allocated-on-live-chips or Pending-with-reason,
#    never a claim pinned to a dead/quarantined chip; the node walk
#    additionally asserts quarantine survives crash-restart), and the
#    HA leader-kill walk (SURVEY §22: two scheduler replicas behind a
#    fenced Lease, leader kills racing pod churn and node-death
#    eviction with sched.lease_renew / sched.takeover_resync armed —
#    never two acting leaders' commits both land, no double
#    allocation, no claim leaked across takeover, at most one acting
#    leader at quiesce).
#    Violations exit non-zero.
# 2. The @slow chaos soak tests (excluded from tier-1 by -m 'not slow').
# 3. Witness cross-validation: the acquisition-order edges the whole
#    matrix + soak observed must be a subset of draracer's static
#    lock-order graph (SURVEY §16.4).
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
SEEDS="${1:-${CHAOS_SEEDS:-25}}"
EVENTS="${2:-${CHAOS_EVENTS:-60}}"
WITNESS_EDGES="$REPO_ROOT/.lockwitness-edges.chaos.json"
# Any matrix violation exports a flight-recorder dump here (SURVEY §19)
# so failed seeds ship their evidence — recent spans, fault firings and
# workqueue events around the violation — next to the logs.
FLIGHTREC_DUMP="${TPU_DRA_FLIGHTREC_DUMP:-$REPO_ROOT/.flightrec.chaos.json}"
rm -f "$WITNESS_EDGES" "$FLIGHTREC_DUMP"

echo ">> chaos matrix: ${SEEDS} seeded schedules x ${EVENTS} events"
JAX_PLATFORMS=cpu TPU_DRA_TPUINFO_BACKEND=fake \
TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" \
TPU_DRA_FLIGHTREC_DUMP="$FLIGHTREC_DUMP" \
  python -m tpu_dra.simcluster.chaos \
    --seeds "$SEEDS" --seed-start "${CHAOS_SEED_START:-0}" \
    --events "$EVENTS" \
  || { echo "!! chaos matrix failed; flight-recorder dump (if any):" \
            "$FLIGHTREC_DUMP"; exit 1; }

echo ">> chaos soak (slow-marked pytest tier, lock witness on)"
JAX_PLATFORMS=cpu TPU_DRA_LOCK_WITNESS=1 \
TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" \
  python -m pytest "$REPO_ROOT/tests/test_chaos.py" \
  -m slow -q -p no:cacheprovider

echo ">> lock-order witness cross-validation (observed ⊆ static)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-witness "$WITNESS_EDGES"
echo ">> chaos tier green"
