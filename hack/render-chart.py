#!/usr/bin/env python3
"""`helm template` analog for environments without the helm binary.

    python hack/render-chart.py [--set key.path=value ...] \
        [--namespace NS] [--release NAME] [--values FILE] [chart_dir]

Renders the chart through tpu_dra.deploy.helmlite and prints a multi-doc
YAML stream suitable for `kubectl apply -f -`. Exits non-zero (with the
template error) on any validation failure — the reference's
`helm template | kubectl apply --dry-run=client` gate.
"""
import argparse
import os
import sys

import yaml

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_dra.deploy.helmlite import TemplateError, render_chart  # noqa: E402

DEFAULT_CHART = os.path.join(os.path.dirname(__file__), "..",
                             "deployments", "helm", "tpu-dra-driver")


def _coerce(v: str):
    if v in ("true", "false"):
        return v == "true"
    try:
        return int(v)
    except ValueError:
        return v


def _set_path(d: dict, dotted: str, value) -> None:
    keys = dotted.split(".")
    for k in keys[:-1]:
        d = d.setdefault(k, {})
    d[keys[-1]] = value


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("chart_dir", nargs="?", default=DEFAULT_CHART)
    ap.add_argument("--set", action="append", default=[], dest="sets",
                    metavar="key.path=value")
    ap.add_argument("--values", "-f", default=None,
                    help="extra values YAML file (merged over defaults)")
    ap.add_argument("--namespace", "-n", default="tpu-dra-driver")
    ap.add_argument("--release", default="tpu-dra-driver")
    args = ap.parse_args()

    overrides: dict = {}
    if args.values:
        with open(args.values) as f:
            overrides = yaml.safe_load(f) or {}
    for s in args.sets:
        if "=" not in s:
            print(f"bad --set {s!r} (need key=value)", file=sys.stderr)
            return 2
        k, v = s.split("=", 1)
        _set_path(overrides, k, _coerce(v))

    try:
        docs = render_chart(args.chart_dir, overrides,
                            release_name=args.release,
                            namespace=args.namespace)
    except TemplateError as e:
        print(f"render error: {e}", file=sys.stderr)
        return 1
    print(yaml.safe_dump_all(docs, default_flow_style=False,
                             sort_keys=False), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
