#!/usr/bin/env python3
"""kubectl subset for clusters without kubectl (the simcluster tier).

Speaks the same HTTP API the drivers use (tpu_dra.k8s.HttpApiClient
against FakeApiServer). Server discovery: --server, $KUBECTL_SHIM_SERVER,
or $KUBECTL_SHIM_STATE (the JSON state file simcluster writes).

Implemented: apply -f FILE|- ; delete KIND NAME | delete -f FILE ;
get KIND [NAME] [-o json|name|jsonpath={.a.b}] ; wait KIND NAME
--for=jsonpath={.path}=value [--timeout=60s] ; logs POD [-c CTR] ;
exec-status. The e2e suite (tests/e2e/*.sh) uses only this subset, so the
same scripts run with real kubectl against a real cluster.
"""

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import yaml  # noqa: E402

from tpu_dra.k8s.client import (  # noqa: E402
    AlreadyExistsError, HttpApiClient, NotFoundError,
)
from tpu_dra.simcluster.gvk import gvr_for_doc, gvr_for_kind, resolve_kind  # noqa: E402


def _client(server: str) -> HttpApiClient:
    if not server:
        server = os.environ.get("KUBECTL_SHIM_SERVER", "")
    if not server and os.environ.get("KUBECTL_SHIM_STATE"):
        with open(os.environ["KUBECTL_SHIM_STATE"]) as f:
            server = json.load(f)["url"]
    if not server:
        print("no server: set --server / KUBECTL_SHIM_SERVER / "
              "KUBECTL_SHIM_STATE", file=sys.stderr)
        sys.exit(2)
    return HttpApiClient(base_url=server)


def _load_docs(path: str):
    text = sys.stdin.read() if path == "-" else open(path).read()
    return [d for d in yaml.safe_load_all(text) if d]


def _jsonpath(obj, expr: str):
    """Minimal jsonpath: {.a.b[0].c}"""
    expr = expr.strip()
    if expr.startswith("{") and expr.endswith("}"):
        expr = expr[1:-1]
    cur = obj
    for part in [p for p in re.split(r"\.", expr) if p]:
        m = re.match(r"^(\w[\w-]*)?(?:\[(\d+)\])?$", part)
        if not m:
            return None
        key, idx = m.group(1), m.group(2)
        if key is not None:
            if not isinstance(cur, dict):
                return None
            cur = cur.get(key)
        if idx is not None:
            if not isinstance(cur, list) or int(idx) >= len(cur):
                return None
            cur = cur[int(idx)]
        if cur is None:
            return None
    return cur


def cmd_apply(args) -> int:
    api = _client(args.server)
    for doc in _load_docs(args.filename):
        gvr = gvr_for_doc(doc)
        ns = doc["metadata"].get("namespace") or (
            args.namespace if gvr.namespaced else None)
        if gvr.namespaced and ns:
            doc["metadata"]["namespace"] = ns
        try:
            api.create(gvr, doc, namespace=ns)
            verb = "created"
        except AlreadyExistsError:
            try:
                current = api.get(gvr, doc["metadata"]["name"], ns)
            except NotFoundError:
                # Exists but not individually addressable (the fake server
                # has no GET route for some cluster-scoped kinds, e.g.
                # namespaces): re-apply is a no-op, like kubectl's
                # "unchanged".
                print(f"{doc['kind'].lower()}/{doc['metadata']['name']} "
                      "unchanged")
                continue
            doc["metadata"]["resourceVersion"] = \
                current["metadata"].get("resourceVersion")
            api.update(gvr, doc, ns)
            verb = "configured"
        print(f"{doc['kind'].lower()}/{doc['metadata']['name']} {verb}")
    return 0


def cmd_delete(args) -> int:
    api = _client(args.server)
    targets = []
    if args.filename:
        for doc in _load_docs(args.filename):
            gvr = gvr_for_doc(doc)
            targets.append((gvr, doc["metadata"]["name"],
                            doc["metadata"].get("namespace")
                            or (args.namespace if gvr.namespaced else None)))
    else:
        kind = resolve_kind(args.kind or "")
        if kind is None:
            print(f"unknown kind {args.kind!r}", file=sys.stderr)
            return 2
        gvr = gvr_for_kind(kind)
        targets.append((gvr, args.name,
                        args.namespace if gvr.namespaced else None))
    rc = 0
    for gvr, name, ns in targets:
        try:
            api.delete(gvr, name, ns)
            print(f"{gvr.plural}/{name} deleted")
        except NotFoundError:
            if not args.ignore_not_found:
                print(f"{gvr.plural}/{name} not found", file=sys.stderr)
                rc = 1
    return rc


def cmd_get(args) -> int:
    api = _client(args.server)
    kind = resolve_kind(args.kind or "")
    if kind is None:
        print(f"unknown kind {args.kind!r}", file=sys.stderr)
        return 2
    gvr = gvr_for_kind(kind)
    ns = args.namespace if gvr.namespaced else None
    if args.name:
        try:
            objs = [api.get(gvr, args.name, ns)]
        except NotFoundError:
            print(f"{gvr.plural}/{args.name} not found", file=sys.stderr)
            return 1
    else:
        objs = api.list(gvr, namespace=ns,
                        label_selector=args.selector or None)
    if args.output == "json":
        doc = objs[0] if args.name else {"apiVersion": "v1", "kind": "List",
                                         "items": objs}
        print(json.dumps(doc, indent=2))
    elif args.output and args.output.startswith("jsonpath="):
        expr = args.output[len("jsonpath="):]
        vals = [_jsonpath(o, expr) for o in objs]
        print(" ".join("" if v is None else
                       (json.dumps(v) if isinstance(v, (dict, list))
                        else str(v)) for v in vals))
    elif args.output == "name":
        for o in objs:
            print(f"{gvr.plural}/{o['metadata']['name']}")
    else:
        for o in objs:
            phase = (o.get("status") or {}).get("phase", "")
            print(f"{o['metadata'].get('namespace', ''):<16}"
                  f"{o['metadata']['name']:<48}{phase}")
    return 0


def _parse_timeout(s: str) -> float:
    m = re.match(r"^(\d+)(s|m)?$", s or "60s")
    if not m:
        return 60.0
    return int(m.group(1)) * (60 if m.group(2) == "m" else 1)


def cmd_wait(args) -> int:
    api = _client(args.server)
    kind = resolve_kind(args.kind or "")
    if kind is None:
        print(f"unknown kind {args.kind!r}", file=sys.stderr)
        return 2
    gvr = gvr_for_kind(kind)
    ns = args.namespace if gvr.namespaced else None
    cond = args.wait_for
    deadline = time.monotonic() + _parse_timeout(args.timeout)

    def satisfied(obj) -> bool:
        if cond.startswith("delete"):
            return False  # handled below
        if cond.startswith("condition="):
            want = cond[len("condition="):]
            name, _, val = want.partition("=")
            val = val or "True"
            for c in (obj.get("status") or {}).get("conditions") or []:
                if c.get("type") == name:
                    return c.get("status") == val
            return False
        if cond.startswith("jsonpath="):
            expr, _, want = cond[len("jsonpath="):].partition("=")
            got = _jsonpath(obj, expr)
            return str(got) == want
        return False

    while time.monotonic() < deadline:
        try:
            obj = api.get(gvr, args.name, ns)
            if cond.startswith("delete"):
                pass
            elif satisfied(obj):
                print(f"{gvr.plural}/{args.name} condition met")
                return 0
        except NotFoundError:
            if cond.startswith("delete"):
                print(f"{gvr.plural}/{args.name} deleted")
                return 0
        time.sleep(0.25)
    print(f"timed out waiting for {cond} on {gvr.plural}/{args.name}",
          file=sys.stderr)
    return 1


def cmd_logs(args) -> int:
    api = _client(args.server)
    state_file = os.environ.get("KUBECTL_SHIM_STATE", "")
    if not state_file:
        print("logs requires KUBECTL_SHIM_STATE (sim mode only)",
              file=sys.stderr)
        return 2
    with open(state_file) as f:
        workdir = json.load(f)["workdir"]
    gvr = gvr_for_kind("Pod")
    try:
        pod = api.get(gvr, args.name, args.namespace)
    except NotFoundError:
        print(f"pod {args.name} not found", file=sys.stderr)
        return 1
    node = pod["spec"].get("nodeName", "")
    uid = pod["metadata"]["uid"]
    ctr = args.container or pod["spec"]["containers"][0]["name"]
    path = os.path.join(workdir, node, "pods", uid, "logs", f"{ctr}.log")
    if not os.path.exists(path):
        print(f"no logs at {path}", file=sys.stderr)
        return 1
    sys.stdout.write(open(path, errors="replace").read())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubectl-shim")
    ap.add_argument("--server", default="")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("apply")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_apply)

    p = sub.add_parser("delete")
    p.add_argument("kind", nargs="?")
    p.add_argument("name", nargs="?")
    p.add_argument("-f", "--filename", default="")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--ignore-not-found", action="store_true")
    p.set_defaults(fn=cmd_delete)

    p = sub.add_parser("get")
    p.add_argument("kind")
    p.add_argument("name", nargs="?")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("-l", "--selector", default="")
    p.add_argument("-o", "--output", default="")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("wait")
    p.add_argument("kind")
    p.add_argument("name")
    p.add_argument("--for", dest="wait_for", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--timeout", default="60s")
    p.set_defaults(fn=cmd_wait)

    p = sub.add_parser("logs")
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_logs)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
