#!/usr/bin/env bash
# One command: cluster up -> full e2e suite -> cluster down.
# (The VERDICT r2 item-3 'done' gate.) Flags pass through to e2e-up.sh.
set -u
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
WORK="$(mktemp -d /tmp/tdra-XXXXXX)"
ENV_FILE="$WORK/env.sh"

# Lint gate before any cluster spin-up: an invariant violation fails in
# seconds here instead of minutes into the e2e run.
"$REPO_ROOT/hack/lint.sh" || exit 1

"$REPO_ROOT/hack/e2e-up.sh" "$ENV_FILE" "$@" || exit 1
# shellcheck disable=SC1090
source "$ENV_FILE"
# Side-metrics (stress churn p95 etc.) land next to the env file and are
# surfaced at the end — the bench-adjacent numbers of the e2e tier.
export E2E_STRESS_METRICS="$WORK/stress-metrics.jsonl"
bash "$REPO_ROOT/tests/e2e/run.sh"
rc=$?
if [ -s "$E2E_STRESS_METRICS" ]; then
  echo "=== e2e side-metrics ==="
  cat "$E2E_STRESS_METRICS"
fi
"$REPO_ROOT/hack/e2e-down.sh" "$ENV_FILE"
exit $rc
