#!/usr/bin/env bash
# Regenerate checked-in protobuf gencode (the check-generate analog of the
# reference's Makefile:104-163 codegen targets).
set -euo pipefail
cd "$(dirname "$0")/.."
protoc -Itpu_dra/kubeletplugin/protos \
  --python_out=tpu_dra/kubeletplugin/gen \
  tpu_dra/kubeletplugin/protos/dra_v1.proto \
  tpu_dra/kubeletplugin/protos/pluginregistration.proto
echo "generated into tpu_dra/kubeletplugin/gen"
