#!/usr/bin/env bash
# Stand up a cluster for the e2e tier and install the chart.
#
#   hack/e2e-up.sh [ENV_FILE] [--nodes N] [--chips N]
#
# Two modes:
#  - kind: when kind+kubectl+docker exist, build the image, create a kind
#    cluster with a fake accel sysfs mounted into each node, install the
#    chart with real kubectl (the reference's demo/clusters/kind story).
#  - sim (default/fallback): start the simcluster (tpu_dra.simcluster) —
#    real driver subprocesses around a fake apiserver — and install the
#    chart through the kubectl shim.
#
# Writes ENV_FILE (default /tmp/tpu-dra-e2e/env.sh) exporting KUBECTL and
# mode details; `source` it, then run tests/e2e/run.sh.
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
ENV_FILE="/tmp/tpu-dra-e2e/env.sh"
NODES=2
CHIPS=4
while [ $# -gt 0 ]; do
  case "$1" in
    --nodes) NODES=$2; shift 2;;
    --chips) CHIPS=$2; shift 2;;
    *) ENV_FILE=$1; shift;;
  esac
done
WORK="$(dirname "$ENV_FILE")"
mkdir -p "$WORK"

if command -v kind >/dev/null && command -v kubectl >/dev/null \
   && command -v docker >/dev/null; then
  echo ">> kind mode"
  IMG=tpu-dra-driver:e2e
  docker build -f "$REPO_ROOT/deployments/container/Dockerfile" \
    -t "$IMG" "$REPO_ROOT"
  # Materialize a fake accel sysfs for each node and mount it where the
  # plugins look (TPUINFO_SYSFS_ROOT=/fake-accel in the values override).
  python "$REPO_ROOT/hack/make-fake-sysfs.py" --out "$WORK/accel" \
    --nodes "$NODES" --chips "$CHIPS"
  {
    echo "kind: Cluster"
    echo "apiVersion: kind.x-k8s.io/v1alpha4"
    echo "nodes:"
    echo "  - role: control-plane"
    for i in $(seq 0 $((NODES - 1))); do
      echo "  - role: worker"
      echo "    labels: {tpu.dev/present: \"true\"}"
      echo "    extraMounts:"
      echo "      - hostPath: $WORK/accel/n$i"
      echo "        containerPath: /fake-accel"
    done
  } > "$WORK/kind.yaml"
  kind create cluster --name tpu-dra-e2e --config "$WORK/kind.yaml"
  kind load docker-image "$IMG" --name tpu-dra-e2e
  python "$REPO_ROOT/hack/render-chart.py" \
    --set image.repository=tpu-dra-driver --set image.tag=e2e \
    -n tpu-dra-driver | kubectl apply -f -
  cat > "$ENV_FILE" <<EOF
export KUBECTL=kubectl
export E2E_MODE=kind
EOF
else
  echo ">> sim mode (kind/kubectl/docker not all present)"
  make -C "$REPO_ROOT/native" -s
  STATE="$WORK/state.json"
  rm -f "$STATE"
  PYTHONPATH="$REPO_ROOT" python -m tpu_dra.simcluster \
    --workdir "$WORK/c" --nodes "$NODES" --chips-per-node "$CHIPS" \
    --state-file "$STATE" > "$WORK/simcluster.log" 2>&1 &
  SIM_PID=$!
  for _ in $(seq 1 50); do
    [ -f "$STATE" ] && break
    kill -0 "$SIM_PID" 2>/dev/null || { cat "$WORK/simcluster.log"; exit 1; }
    sleep 0.2
  done
  [ -f "$STATE" ] || { echo "simcluster never became ready"; exit 1; }
  export KUBECTL_SHIM_STATE="$STATE"
  PYTHONPATH="$REPO_ROOT" python "$REPO_ROOT/hack/render-chart.py" \
    -n tpu-dra-driver \
    | PYTHONPATH="$REPO_ROOT" python "$REPO_ROOT/hack/kubectl_shim.py" \
        apply -f - >/dev/null
  cat > "$ENV_FILE" <<EOF
export KUBECTL="python $REPO_ROOT/hack/kubectl_shim.py"
export KUBECTL_SHIM_STATE="$STATE"
export E2E_MODE=sim
export E2E_SIM_PID=$SIM_PID
export PYTHONPATH="$REPO_ROOT"
EOF
fi
echo ">> cluster up; source $ENV_FILE then run tests/e2e/run.sh"
