#!/usr/bin/env bash
# Tear down whatever hack/e2e-up.sh stood up.
set -u
ENV_FILE="${1:-/tmp/tpu-dra-e2e/env.sh}"
[ -f "$ENV_FILE" ] || { echo "no env file $ENV_FILE"; exit 0; }
# shellcheck disable=SC1090
source "$ENV_FILE"
if [ "${E2E_MODE:-sim}" = "kind" ]; then
  kind delete cluster --name tpu-dra-e2e || true
else
  if [ -n "${E2E_SIM_PID:-}" ]; then
    kill "$E2E_SIM_PID" 2>/dev/null || true
    for _ in $(seq 1 50); do
      kill -0 "$E2E_SIM_PID" 2>/dev/null || break
      sleep 0.2
    done
    kill -9 "$E2E_SIM_PID" 2>/dev/null || true
  fi
fi
rm -rf "$(dirname "$ENV_FILE")"
echo ">> cluster down"
