#!/usr/bin/env bash
# drmc tier (SURVEY §13): the deterministic model checker as a CI gate.
#
#   hack/drmc.sh [BUDGET]
#
# Runs `python -m tpu_dra.analysis.drmc` over the gate scenarios:
#
# 1. Interleaving explorer — DPOR-lite systematic exploration of the
#    scheduler-churn (MULTI-WORKER WorkQueue pool + sharded
#    AllocationIndex, with a per-key serialization probe),
#    batch-prepare (concurrent DeviceState batches), and evict-churn
#    (eviction racing the optimistic bind pipeline, SURVEY §18)
#    scenarios, asserting the chaos invariants (no double allocation,
#    index == truth, checkpoint/CDI consistency, no claim bound to a
#    dead device post-eviction, acyclic lock witness) at EVERY
#    terminal state. The
#    gate requires >= 200 distinct interleavings total (--min-schedules)
#    so a silently shrunken scenario cannot go green by exploring
#    nothing; a SECOND dedicated run holds the evict-churn scenario
#    ALONE to >= 200 interleavings (the ISSUE 12 acceptance bar), a
#    THIRD holds takeover-resync (deposed-leader commits vs. the HA
#    takeover's bump-then-resync against the real fencing reactor,
#    SURVEY §22) to the same >= 200-interleaving floor (ISSUE 16), and
#    a FOURTH holds shard-dispatch (the partitioned informer's bounded
#    per-shard FIFOs: watcher-queue overflow vs. relist healing vs.
#    mid-stream stop(), SURVEY §24) to >= 200 interleavings (ISSUE 18).
# 2. Crash-point enumerator — 100% of the batch-prepare-crash AND
#    quarantine-crash (chip-quarantine journal ops interleaved with a
#    claim lifecycle) scenarios' durable ops crashed (clean /
#    all-persisted / torn variants) with recovery invariants asserted
#    after each restart. Since ISSUE 17 the batch-prepare-crash
#    scenario forces both binary-journal rotations — compaction
#    retirement (journal_compact_lag=2) and the size roll
#    (segment_roll_bytes=64) — so segment creates, old-chain unlinks,
#    deferred dir syncs, and torn BINARY record tails are all in the
#    enumerated set.
#
# Any invariant violation fails with the schedule trace (or crash
# point) printed; replay the trace with:
#   python -m tpu_dra.analysis.drmc --scenario NAME --replay-trace '[...]'
# Extra arguments after BUDGET pass straight through to the module
# (race.sh uses `drmc.sh 600 --skip-crash` for its deep re-exploration:
# the crash matrix is budget-independent and already ran in lint.sh).
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUDGET="${1:-200}"
shift || true

echo ">> drmc: interleaving exploration + crash-point enumeration"
JAX_PLATFORMS=cpu python -m tpu_dra.analysis.drmc \
  --budget "$BUDGET" --min-schedules 200 --min-crash-points 30 \
  --deadline 180 "$@"

echo ">> drmc: evict-churn dedicated floor (>= 200 interleavings)"
JAX_PLATFORMS=cpu python -m tpu_dra.analysis.drmc \
  --scenario evict-churn --budget 250 --min-schedules 200 \
  --deadline 120 --skip-crash

echo ">> drmc: takeover-resync dedicated floor (>= 200 interleavings)"
JAX_PLATFORMS=cpu python -m tpu_dra.analysis.drmc \
  --scenario takeover-resync --budget 250 --min-schedules 200 \
  --deadline 120 --skip-crash

echo ">> drmc: shard-dispatch dedicated floor (>= 200 interleavings)"
JAX_PLATFORMS=cpu python -m tpu_dra.analysis.drmc \
  --scenario shard-dispatch --budget 250 --min-schedules 200 \
  --deadline 120 --skip-crash

echo ">> drmc tier green"
