#!/usr/bin/env bash
# Lint tier (the reference's `go vet` + golangci-lint analog, Makefile):
#
#   hack/lint.sh
#
# 1. compileall — syntax over the whole tree (dralint skips files that
#    do not parse; this step makes them loud).
# 2. dralint — the project-invariant analyzer (tpu_dra/analysis):
#    R1 *_locked call discipline, R2 no blocking work under data locks,
#    R3 zero-copy informer reads are read-only, R4 fault-site registry
#    coverage, R5 metric catalog, R6 feature-gate names. Any
#    unsuppressed finding fails.
# 3. The fault-site coverage report (informational): guard + arm
#    locations per registered site.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

echo ">> compileall"
python -m compileall -q \
  "$REPO_ROOT/tpu_dra" "$REPO_ROOT/tests" "$REPO_ROOT/bench.py" \
  "$REPO_ROOT/hack"

echo ">> dralint (R1-R6) + fault-site coverage"
python -m tpu_dra.analysis --root "$REPO_ROOT" --sites-report

echo ">> lint tier green"
