#!/usr/bin/env bash
# Lint tier (the reference's `go vet` + golangci-lint analog, Makefile):
#
#   hack/lint.sh
#
# 1. compileall — syntax over the whole tree (dralint skips files that
#    do not parse; this step makes them loud).
# 2. dralint — the project-invariant analyzer (tpu_dra/analysis):
#    R1 *_locked call discipline, R2 no blocking work under data locks,
#    R3 zero-copy informer reads are read-only, R4 fault-site registry
#    coverage, R5 metric catalog, R6 feature-gate names, R7 prepare-
#    pipeline except paths unwind, R8 no success externalization before
#    the terminal store, R12 span begin/end discipline (every
#    tracer.begin outside a with-form must end()/abandon() on all
#    paths — SURVEY §19) — plus the draracer interprocedural pass
#    (SURVEY §16): R9 whole-tree *_locked reachability over the call
#    graph, R10 guarded-by inference, R11 static lock-order graph
#    acyclicity. Any unsuppressed finding fails, and so does any
#    suppression comment WITHOUT a justification string
#    (--require-justified): the waiver count can never grow silently.
#    Whole-tree runs are incremental (per-file result cache,
#    .dralint-cache.json); DRALINT_NO_CACHE=1 forces a cold run.
# 3. The fault-site coverage report (informational): guard + arm
#    locations per registered site.
# 4. drmc — the deterministic model checker gate (hack/drmc.sh):
#    interleaving exploration + crash-point enumeration over the
#    scheduler-churn and batch-prepare scenarios — run with the lock
#    witness EXPORTING its observed acquisition-order edges.
# 5. observed ⊆ static: every runtime edge the drmc run observed must
#    be in R11's static lock-order graph. An unexplained edge means
#    the call graph under-approximates — the gate fails so the model
#    gets fixed rather than quietly trusted.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
WITNESS_EDGES="$REPO_ROOT/.lockwitness-edges.lint.json"

echo ">> compileall"
python -m compileall -q \
  "$REPO_ROOT/tpu_dra" "$REPO_ROOT/tests" "$REPO_ROOT/bench.py" \
  "$REPO_ROOT/hack"

echo ">> dralint (R1-R12) + fault-site coverage"
python -m tpu_dra.analysis --root "$REPO_ROOT" --sites-report \
  --require-justified ${DRALINT_NO_CACHE:+--no-cache}

rm -f "$WITNESS_EDGES"
TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" "$REPO_ROOT/hack/drmc.sh"

echo ">> lock-order witness cross-validation (observed ⊆ static)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-witness "$WITNESS_EDGES" ${DRALINT_NO_CACHE:+--no-cache}

echo ">> lint tier green"
