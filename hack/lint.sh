#!/usr/bin/env bash
# Lint tier (the reference's `go vet` + golangci-lint analog, Makefile):
#
#   hack/lint.sh
#
# 1. compileall — syntax over the whole tree (dralint skips files that
#    do not parse; this step makes them loud).
# 2. dralint — the project-invariant analyzer (tpu_dra/analysis):
#    R1 *_locked call discipline, R2 no blocking work under data locks,
#    R3 zero-copy informer reads are read-only, R4 fault-site registry
#    coverage, R5 metric catalog, R6 feature-gate names, R7 prepare-
#    pipeline except paths unwind, R8 no success externalization before
#    the terminal store, R12 span begin/end discipline (every
#    tracer.begin outside a with-form must end()/abandon() on all
#    paths — SURVEY §19) — plus the draracer interprocedural pass
#    (SURVEY §16): R9 whole-tree *_locked reachability over the call
#    graph, R10 guarded-by inference, R11 static lock-order graph
#    acyclicity — plus drflow (SURVEY §20): R13 whole-tree escape
#    analysis of zero-copy informer views, R14 stale-snapshot
#    check-then-act across lock releases (REVALIDATES protocol
#    annotations), R15 swallowed-exception audit w/ declared fault-site
#    degradations. Any unsuppressed finding fails, and so does any
#    suppression comment WITHOUT a justification string
#    (--require-justified): the waiver count can never grow silently.
#    Whole-tree runs are incremental (per-file result cache,
#    .dralint-cache.json); DRALINT_NO_CACHE=1 forces a cold run; the
#    scan phase parallelizes with --jobs (DRALINT_JOBS, default auto)
#    and a cold run is wall-clock-gated so extraction cost cannot
#    silently regress. The per-rule findings/suppressions/timing table
#    renders after every run.
# 3. The fault-site coverage report (informational): guard + arm
#    locations per registered site.
# 4. View-shadow cross-validation (SURVEY §20): a seeded scheduler
#    chaos walk runs with every zero-copy view content-hashed at
#    hand-out and re-hashed at quiesce; any in-place mutation fails the
#    walk, and the exported drift set must map to statically
#    R13-implicated view seeds (observed ⊆ static, both directions:
#    the drmc stale-read probe is R14's runtime half).
# 5. drmc — the deterministic model checker gate (hack/drmc.sh):
#    interleaving exploration + crash-point enumeration over the
#    scheduler-churn, batch-prepare, evict-churn and stale-read-fixed
#    scenarios — run with the lock witness EXPORTING its observed
#    acquisition-order edges.
# 6. observed ⊆ static: every runtime edge the drmc run observed must
#    be in R11's static lock-order graph. An unexplained edge means
#    the call graph under-approximates — the gate fails so the model
#    gets fixed rather than quietly trusted.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
WITNESS_EDGES="$REPO_ROOT/.lockwitness-edges.lint.json"

echo ">> compileall"
python -m compileall -q \
  "$REPO_ROOT/tpu_dra" "$REPO_ROOT/tests" "$REPO_ROOT/bench.py" \
  "$REPO_ROOT/hack"

echo ">> dralint (R1-R15) + fault-site coverage + per-rule table"
python -m tpu_dra.analysis --root "$REPO_ROOT" --sites-report \
  --rule-table --jobs "${DRALINT_JOBS:-auto}" \
  --require-justified ${DRALINT_NO_CACHE:+--no-cache}

echo ">> dralint cold-run wall-clock gate (--jobs, no cache)"
# The parallel-extraction satellite's regression bound: a COLD
# whole-tree run (no result cache read or written) must finish inside
# the timeout even as the rule families grow — if this trips, the
# extraction got slower, not the machine.
timeout 180 python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --no-cache --jobs "${DRALINT_JOBS:-auto}" >/dev/null

echo ">> view-shadow chaos walk (drflow R13 runtime cross-validation)"
# One seeded scheduler-churn walk with the zero-copy view shadow
# enabled: quiesce fails on any in-place view mutation, and the drift
# set is exported for the observed⊆static check below.
VIEW_DRIFTS="$REPO_ROOT/.viewshadow-drifts.lint.json"
rm -f "$VIEW_DRIFTS"
TPU_DRA_VIEW_SHADOW_EXPORT="$VIEW_DRIFTS" JAX_PLATFORMS=cpu python - <<'PY'
from tpu_dra.simcluster.chaos import run_sched_schedule
r = run_sched_schedule(11, 40)
if not r.ok:
    print("view-shadow chaos walk violations:")
    for v in r.violations:
        print("  ", v)
raise SystemExit(0 if r.ok else 1)
PY

echo ">> view-shadow cross-validation (observed drifts ⊆ static R13)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-view-shadow "$VIEW_DRIFTS" ${DRALINT_NO_CACHE:+--no-cache}

rm -f "$WITNESS_EDGES"
TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" "$REPO_ROOT/hack/drmc.sh"

echo ">> lock-order witness cross-validation (observed ⊆ static)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-witness "$WITNESS_EDGES" ${DRALINT_NO_CACHE:+--no-cache}

echo ">> lint tier green"
