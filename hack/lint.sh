#!/usr/bin/env bash
# Lint tier (the reference's `go vet` + golangci-lint analog, Makefile):
#
#   hack/lint.sh
#
# 1. compileall — syntax over the whole tree (dralint skips files that
#    do not parse; this step makes them loud).
# 2. dralint — the project-invariant analyzer (tpu_dra/analysis):
#    R1 *_locked call discipline, R2 no blocking work under data locks,
#    R3 zero-copy informer reads are read-only, R4 fault-site registry
#    coverage, R5 metric catalog, R6 feature-gate names, R7 prepare-
#    pipeline except paths unwind, R8 no success externalization before
#    the terminal store. Any unsuppressed finding fails. Whole-tree
#    runs are incremental (per-file result cache, .dralint-cache.json);
#    DRALINT_NO_CACHE=1 forces a cold run.
# 3. The fault-site coverage report (informational): guard + arm
#    locations per registered site.
# 4. drmc — the deterministic model checker gate (hack/drmc.sh):
#    interleaving exploration + crash-point enumeration over the
#    scheduler-churn and batch-prepare scenarios.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

echo ">> compileall"
python -m compileall -q \
  "$REPO_ROOT/tpu_dra" "$REPO_ROOT/tests" "$REPO_ROOT/bench.py" \
  "$REPO_ROOT/hack"

echo ">> dralint (R1-R8) + fault-site coverage"
python -m tpu_dra.analysis --root "$REPO_ROOT" --sites-report \
  ${DRALINT_NO_CACHE:+--no-cache}

"$REPO_ROOT/hack/drmc.sh"

echo ">> lint tier green"
