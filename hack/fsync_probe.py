#!/usr/bin/env python3
"""Measure this box's fdatasync floor — the physics under every perf gate.

The durable-commit path's latency decomposes into (a) Python/serialization
work the storage engine can optimize and (b) the device's flush latency,
which it cannot. Absolute p50 gates conflate the two and trip on slower
hosts (the PR 16 finding: a laptop-class NVMe syncs in ~0.05ms, a cloud
boot disk in ~1ms+). This probe measures (b) directly — an in-place 4KiB
pwrite + fdatasync on a preallocated file in the target directory, the
exact op the journal's group-sync leader performs — so perf.sh can budget
its gates relative to the floor instead of hardcoding one box's numbers.

The probe has a second term: --cpu measures a single-core CPU
reference (min-of-samples over a fixed serialization-shaped workload),
because a floor-only budget still conflates device speed with how fast
this box runs the Python between syncs — see measure_cpu below.

Usage:
    hack/fsync_probe.py [DIR] [--iters N] [--cpu] [--json]

Prints the floor p50 in milliseconds on stdout (one number, shell-
consumable) by default; --cpu prints the CPU reference instead; --json
emits the full percentile breakdown plus the CPU reference.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def measure(directory: str, iters: int = 200, size: int = 4096):
    """p50/p90/p99 of an in-place pwrite+fdatasync cycle, in ms."""
    fdatasync = getattr(os, "fdatasync", os.fsync)
    fd, path = tempfile.mkstemp(prefix=".fsync_probe_", dir=directory)
    try:
        # Preallocate + settle so the measured loop never extends the
        # file: extension turns fdatasync into fsync-with-metadata and
        # overstates the floor (same reason the journal preallocates).
        os.pwrite(fd, b"\0" * size, 0)
        os.fsync(fd)
        block = b"\x5a" * size
        samples = []
        for i in range(iters):
            t0 = time.perf_counter()
            os.pwrite(fd, block, 0)
            fdatasync(fd)
            samples.append((time.perf_counter() - t0) * 1000.0)
    finally:
        os.close(fd)
        try:
            os.unlink(path)
        except OSError:
            pass
    samples.sort()

    def pct(p):
        return samples[min(len(samples) - 1, int(len(samples) * p))]

    return {
        "dir": directory,
        "iters": iters,
        "write_bytes": size,
        "fdatasync_floor_p50_ms": round(pct(0.50), 4),
        "fdatasync_floor_p90_ms": round(pct(0.90), 4),
        "fdatasync_floor_p99_ms": round(pct(0.99), 4),
        "fdatasync_floor_min_ms": round(samples[0], 4),
    }


def measure_cpu(iters: int = 100) -> float:
    """Single-core CPU reference, in ms: the MINIMUM over `iters` runs
    of a fixed serialization-shaped workload (dict build + sorted
    json.dumps + crc32 + loads — the kind of Python the prepare
    pipeline spends its non-sync time on). The fdatasync floor captures
    the storage device but says nothing about how fast this box runs
    Python; an absolute software allowance on top of the floor still
    trips on a slow core (the PR 17 finding: one host ran the identical
    hot path ~1.7x slower than the box that calibrated the old 1.0ms
    gate). The minimum — not the median — is the stable statistic: it
    measures the core with scheduler noise excluded (same rationale as
    timeit's best-of)."""
    import zlib

    def one() -> float:
        doc = {
            "claims": {
                "uid-%d" % j: {
                    "devices": ["chip-%d" % k for k in range(4)],
                    "seq": j,
                    "env": {"TPU_CHIPS": "0,1,2,3",
                            "TPU_WORKER_ID": str(j)},
                    "cdi": ["tpu.google.com/device=chip-%d" % k
                            for k in range(4)],
                } for j in range(8)
            },
            "node": "node-0", "generation": 12345,
        }
        t0 = time.perf_counter()
        for _ in range(6):
            s = json.dumps(doc, sort_keys=True)
            zlib.crc32(s.encode())
            json.loads(s)
        return (time.perf_counter() - t0) * 1000.0

    one()  # warm the allocator / code paths outside the sample set
    return round(min(one() for _ in range(iters)), 4)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", default=tempfile.gettempdir(),
                    help="directory to probe (default: system tmpdir; "
                         "pass the checkpoint dir for the real device)")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--cpu", action="store_true",
                    help="print the CPU reference (ms) instead of the "
                         "fdatasync floor")
    ap.add_argument("--json", action="store_true",
                    help="full percentile breakdown instead of bare p50")
    args = ap.parse_args(argv)
    if args.cpu and not args.json:
        print(measure_cpu())
        return 0
    result = measure(args.dir, iters=args.iters)
    if args.json:
        result["cpu_ref_ms"] = measure_cpu()
        print(json.dumps(result))
    else:
        print(result["fdatasync_floor_p50_ms"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
