#!/usr/bin/env bash
# Race-hunting tier (the reference's `go test -race` analog, Makefile:96):
#
#   hack/race.sh [ITERATIONS]
#
# 1. Lint gate first: dralint's static rules are the cheap half of the
#    race tier — a blocking call under a data lock fails here before any
#    TSan cycle is spent. (lint.sh also runs drmc at the default budget.)
# 2. drmc at a deeper exploration budget: the race tier buys more
#    distinct interleavings of the scheduler-churn and batch-prepare
#    scenarios than the per-PR lint gate pays for. --skip-crash: the
#    crash matrix is budget-independent and lint.sh just ran it.
# 3. Builds the threaded C++ daemons under ThreadSanitizer and drives them
#    with concurrent clients (TSAN_OPTIONS halt_on_error: any report fails).
# 4. Repeat-runs the heavily threaded Python suites (informers, workqueues,
#    three-process CD convergence, watchdogs) N times — the flake surface
#    scales with iterations, not wall-clock — with the LOCK-ORDER WITNESS
#    installed (TPU_DRA_LOCK_WITNESS=1): conftest fails the session on an
#    acquisition-order cycle.
# 5. Witness cross-validation: every acquisition-order edge OBSERVED
#    across the deep drmc exploration and all N witnessed suite runs
#    must be in draracer's static lock-order graph (observed ⊆ static,
#    SURVEY §16.4) — an unexplained edge means the static call graph
#    under-approximates and fails the tier.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
N="${1:-3}"
WITNESS_EDGES="$REPO_ROOT/.lockwitness-edges.race.json"
VIEW_DRIFTS="$REPO_ROOT/.viewshadow-drifts.race.json"
rm -f "$WITNESS_EDGES" "$VIEW_DRIFTS"

echo ">> lint gate (dralint)"
"$REPO_ROOT/hack/lint.sh"

echo ">> drmc deep exploration"
TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" \
  "$REPO_ROOT/hack/drmc.sh" 600 --skip-crash

echo ">> TSan build + drive"
make -C "$REPO_ROOT/native" tsan -s
export TSAN_OPTIONS="halt_on_error=1 exitcode=66"
TSAN_COORD="$REPO_ROOT/native/build-tsan/tpu-multiprocess-coordinator" \
TSAN_DAEMON="$REPO_ROOT/native/build-tsan/tpu-slice-daemon" \
  python "$REPO_ROOT/hack/tsan_drive.py"

echo ">> ${N}x repeat of the threaded Python suites (lock witness on)"
for i in $(seq 1 "$N"); do
  echo "-- iteration $i/$N"
  TPU_DRA_LOCK_WITNESS=1 \
  TPU_DRA_LOCK_WITNESS_EXPORT="$WITNESS_EDGES" \
  TPU_DRA_VIEW_SHADOW=1 \
  TPU_DRA_VIEW_SHADOW_EXPORT="$VIEW_DRIFTS" \
  python -m pytest "$REPO_ROOT/tests/test_cd_integration.py" \
    "$REPO_ROOT/tests/test_stress_failover.py" \
    "$REPO_ROOT/tests/test_multiprocess_e2e.py" -q -p no:cacheprovider
done

echo ">> lock-order witness cross-validation (observed ⊆ static)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-witness "$WITNESS_EDGES"

echo ">> view-shadow cross-validation (observed drifts ⊆ static R13)"
python -m tpu_dra.analysis --root "$REPO_ROOT" \
  --check-view-shadow "$VIEW_DRIFTS"

echo ">> race tier green"
