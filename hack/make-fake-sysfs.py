#!/usr/bin/env python3
"""Materialize fake accel sysfs trees for kind-node mounts.

    python hack/make-fake-sysfs.py --out DIR --nodes N --chips M

One tree per node under DIR/n<i>, each the ABI tpu_dra.native reads
(chips, topology, PCI/IOMMU for passthrough). Used by hack/e2e-up.sh's
kind mode; the simcluster materializes its own trees in-process.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_dra.native.tpuinfo import default_fake_chips, make_fake_sysfs  # noqa: E402

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--chips", type=int, default=4)
    # v5p default (2 TensorCores/chip) to match the simcluster: the
    # subslice demo needs chips that can be subdivided.
    from tpu_dra.native.tpuinfo import GEN_SPECS  # noqa: E402
    ap.add_argument("--generation", default="v5p",
                    choices=sorted(GEN_SPECS))
    ap.add_argument("--slice-id", default="slice-A")
    args = ap.parse_args()
    for i in range(args.nodes):
        root = os.path.join(args.out, f"n{i}")
        make_fake_sysfs(root, default_fake_chips(
            args.chips, args.generation, args.slice_id, i))
        print(f"wrote {root}")
